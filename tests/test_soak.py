"""Closed-loop soak: labeled campaigns scored against a live daemon.

The runner's one job is honest bookkeeping: a verdict that matches its
ground-truth label is ``ok``, a contradiction is a ``false_verdict`` that
must page + dump + exit nonzero, and anything the loop cannot score
(UNKNOWN, lost submits, unconfirmed labels) must surface as its own
outcome instead of passing silently.
"""

import http.server
import io
import json
import os
import threading

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.collector.adversarial import adversarial_events
from s2_verification_tpu.collector.campaign import collect_labeled, get_campaign
from s2_verification_tpu.obs.flight import read_flight
from s2_verification_tpu.service.cache import history_fingerprint
from s2_verification_tpu.service.client import VerifydClient
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.soak import (
    SoakConfig,
    SoakRunner,
    repro_command,
    soak_exit_code,
)
from s2_verification_tpu.utils import events as ev


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("soak-daemon")
    cfg = VerifydConfig(
        socket_path=str(tmp / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=30.0,
        out_dir=str(tmp / "viz"),
        stats_log=str(tmp / "stats.jsonl"),
    )
    with Verifyd(cfg):
        yield cfg


def _scfg(daemon, **kw) -> SoakConfig:
    base = dict(address=daemon.socket_path, seed=11, retries=3, backoff_s=0.05)
    base.update(kw)
    return SoakConfig(**base)


# -- scoring -----------------------------------------------------------------


def test_clean_run_scores_every_label(daemon):
    runner = SoakRunner(_scfg(daemon, campaigns=("steady", "drop-acked")))
    summary = runner.run()
    assert soak_exit_code(summary) == 0
    assert summary["verdict_table"] == {
        "legal->legal": 1,
        "illegal->illegal": 1,
    }
    assert summary["ok"] == summary["submitted"] == 2
    assert not summary["false_verdicts"] and not summary["submit_errors"]
    assert runner._m_verdicts.value(expected="illegal", actual="illegal") == 1


def test_schedule_is_deterministic_and_cycle_spread():
    cfg = SoakConfig(address="ignored", campaigns=("a", "b"), seed=5, cycles=2)
    sched = SoakRunner(cfg).schedule()
    assert sched == SoakRunner(cfg).schedule()
    assert len(sched) == 4
    assert len({s for _, s in sched}) == 4, "every run gets a distinct seed"


def test_soak_exit_code_taxonomy():
    clean = dict(false_verdicts=[], submit_errors=[], inconclusive=0, unlabeled=0)
    assert soak_exit_code(clean) == 0
    assert soak_exit_code({**clean, "false_verdicts": [{}]}) == 1
    assert soak_exit_code({**clean, "submit_errors": [{}]}) == 3
    assert soak_exit_code({**clean, "inconclusive": 1}) == 3
    assert soak_exit_code({**clean, "unlabeled": 1}) == 3


def test_unreachable_daemon_is_a_lost_submit_not_a_crash(tmp_path):
    cfg = SoakConfig(
        address=str(tmp_path / "nobody-home.sock"),
        campaigns=("steady",),
        seed=11,
        retries=1,
        backoff_s=0.01,
    )
    summary = SoakRunner(cfg).run()
    assert soak_exit_code(summary) == 3
    assert len(summary["submit_errors"]) == 1
    assert summary["results"][0]["outcome"] == "submit_error"


# -- the sentinel ------------------------------------------------------------


class _Sink(http.server.ThreadingHTTPServer):
    def __init__(self):
        self.alerts = []
        sink = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n))
                # Alertmanager v1 webhook shape: a JSON list of alerts.
                sink.alerts.extend(body if isinstance(body, list) else [])
                self.send_response(200)
                self.end_headers()

            def log_message(self, *_):
                pass

        super().__init__(("127.0.0.1", 0), Handler)
        self.daemon_threads = True
        threading.Thread(target=self.serve_forever, daemon=True).start()


def test_mislabeled_control_fires_the_false_verdict_path(daemon, tmp_path):
    sink = _Sink()
    try:
        state = str(tmp_path / "state")
        runner = SoakRunner(
            _scfg(
                daemon,
                campaigns=("steady",),
                mislabel_first=True,
                alert_url=f"http://127.0.0.1:{sink.server_address[1]}/alerts",
                state_dir=state,
            )
        )
        summary = runner.run()
        assert soak_exit_code(summary) == 1
        (row,) = summary["false_verdicts"]
        assert row["control"] and row["expect"] == "illegal"
        assert row["actual"] == "legal"
        assert runner._m_false.value(campaign="steady") == 1
        # Webhook: the builtin checker_false_verdict alert was delivered.
        names = [a.get("labels", {}).get("alertname") for a in sink.alerts]
        assert "checker_false_verdict" in names
        # Flight marker: fingerprint + repro for one-command reproduction.
        marks = [
            m
            for m in read_flight(state)
            if m.get("k") == "dump" and m.get("reason") == "checker_false_verdict"
        ]
        assert len(marks) == 1
        assert marks[0]["fingerprint"] == row["fingerprint"]
        assert "--campaign steady --seed" in marks[0]["repro"]
        # The offending history + label landed on disk.
        d = os.path.join(state, "false_verdicts")
        saved = sorted(os.listdir(d))
        assert any(p.endswith(".jsonl") for p in saved)
        assert any(p.endswith(".label.json") for p in saved)
    finally:
        sink.shutdown()
        sink.server_close()


def test_repro_command_regenerates_the_flagged_bytes():
    events, label = collect_labeled(get_campaign("reorder"), seed=11)
    fp = history_fingerprint(prepare(events))
    cmd = repro_command(label)
    assert "--campaign reorder --seed 11" in cmd
    # Replaying the label's (campaign, seed, sizing) reproduces the exact
    # fingerprint the sentinel flagged.
    again, _ = collect_labeled(
        get_campaign(label["campaign"]),
        label["seed"],
        clients=label["clients"],
        ops=label["ops"],
    )
    assert history_fingerprint(prepare(again)) == fp


# -- adversarial histories through the live daemon ---------------------------


def test_unsatisfiable_adversarial_history_is_illegal_via_submit(daemon):
    events = adversarial_events(5, batch=4, seed=2, unsatisfiable=True)
    buf = io.StringIO()
    ev.write_history(events, buf)
    client = VerifydClient(daemon.socket_path, timeout=60)
    reply = client.submit_with_retry(buf.getvalue(), client="test", no_viz=True)
    assert int(reply["verdict"]) == 1, reply  # ILLEGAL


def test_satisfiable_adversarial_history_is_legal_via_submit(daemon):
    events = adversarial_events(5, batch=4, seed=2)
    buf = io.StringIO()
    ev.write_history(events, buf)
    client = VerifydClient(daemon.socket_path, timeout=60)
    reply = client.submit_with_retry(buf.getvalue(), client="test", no_viz=True)
    assert int(reply["verdict"]) == 0, reply  # OK


# -- the full matrix (slow: soak_check covers this against a fleet) ----------


@pytest.mark.slow
def test_full_builtin_matrix_clean_against_daemon(daemon, tmp_path):
    runner = SoakRunner(
        _scfg(daemon, state_dir=str(tmp_path / "state"))
    )
    summary = runner.run()
    assert soak_exit_code(summary) == 0, summary["verdict_table"]
    assert summary["verdict_table"].get("illegal->illegal") == 4
