"""Device-lease allocator unit tests (service/devicepool.py): sizing
policy, buddy alignment, blocking contention, timeout fallback, and the
ServiceStats lease event stream.  Pure threading — no jax, no daemon."""

from __future__ import annotations

import io
import json
import threading
import time

import pytest

from s2_verification_tpu.service.devicepool import (
    DevicePool,
    lease_size_for,
)
from s2_verification_tpu.service.stats import ServiceStats

# -- sizing policy -----------------------------------------------------------


@pytest.mark.parametrize(
    "shape,total,want",
    [
        # small jobs stay single-chip (escalation is already the slow path)
        ("16x2x8", 8, 1),
        ("32x3x8", 8, 1),
        # chains >= 4 or ops >= 64 -> 2
        ("16x4x8", 8, 2),
        ("64x2x8", 8, 2),
        # chains >= 8 or ops >= 256 -> 4
        ("64x8x8", 8, 4),
        ("256x2x8", 8, 4),
        # chains >= 12 or ops >= 1024 -> 8
        ("64x12x8", 8, 8),
        ("1024x2x8", 8, 8),
        # clamped to the largest power of two <= total
        ("1024x12x8", 4, 4),
        ("1024x12x8", 6, 4),
        ("1024x12x8", 1, 1),
        # malformed shapes degrade to 1, never raise
        ("", 8, 1),
        ("garbage", 8, 1),
        (None, 8, 1),
    ],
)
def test_lease_size_policy(shape, total, want):
    assert lease_size_for(shape, total) == want


def test_grants_are_power_of_two_and_aligned():
    pool = DevicePool(8)
    for shape in ("16x4x8", "64x8x8", "64x12x8", "16x2x8"):
        lease = pool.acquire(shape=shape, timeout_s=0)
        assert lease is not None
        size = lease.size
        assert size & (size - 1) == 0  # power of two
        assert lease.indices[0] % size == 0  # aligned
        assert lease.indices == tuple(
            range(lease.indices[0], lease.indices[0] + size)
        )  # contiguous
        pool.release(lease)


# -- allocation ---------------------------------------------------------------


def test_disjoint_grants_and_reuse_after_release():
    pool = DevicePool(8)
    a = pool.acquire(size=4, timeout_s=0)
    b = pool.acquire(size=2, timeout_s=0)
    c = pool.acquire(size=2, timeout_s=0)
    assert a and b and c
    taken = set(a.indices) | set(b.indices) | set(c.indices)
    assert len(taken) == 8  # all disjoint, pool exactly full
    assert pool.acquire(size=1, timeout_s=0) is None
    pool.release(b)
    d = pool.acquire(size=2, timeout_s=0)
    assert d is not None and set(d.indices) == set(b.indices)
    for lease in (a, c, d):
        pool.release(lease)
    assert pool.snapshot()["in_use"] == 0


def test_oversized_request_clamps_to_pool():
    pool = DevicePool(2)
    lease = pool.acquire(shape="1024x12x8", timeout_s=0)
    assert lease is not None and lease.size == 2
    pool.release(lease)


def test_double_release_raises():
    pool = DevicePool(2)
    lease = pool.acquire(size=1, timeout_s=0)
    pool.release(lease)
    with pytest.raises(ValueError):
        pool.release(lease)


def test_contention_blocks_then_wakes_waiter():
    pool = DevicePool(2)
    first = pool.acquire(size=2, timeout_s=0)
    got = []

    def waiter():
        got.append(pool.acquire(size=2, timeout_s=10))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while pool.snapshot()["waiters"] == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pool.snapshot()["waiters"] == 1  # blocked, not failed
    pool.release(first)
    t.join(timeout=10)
    assert not t.is_alive()
    assert got and got[0] is not None and got[0].size == 2
    pool.release(got[0])


def test_timeout_returns_none_and_pool_survives():
    pool = DevicePool(1)
    held = pool.acquire(size=1, timeout_s=0)
    t0 = time.monotonic()
    assert pool.acquire(size=1, timeout_s=0.05) is None
    assert time.monotonic() - t0 < 5.0
    pool.release(held)
    again = pool.acquire(size=1, timeout_s=0)
    assert again is not None
    pool.release(again)


# -- stats events -------------------------------------------------------------


def _events(sink: io.StringIO) -> list[dict]:
    return [json.loads(l) for l in sink.getvalue().splitlines() if l.strip()]


def test_lease_events_drive_stats_stream_and_registry():
    sink = io.StringIO()
    stats = ServiceStats(sink)
    pool = DevicePool(4, stats=stats)

    lease = pool.acquire(shape="64x8x8", job=7, timeout_s=0)
    assert lease is not None and lease.size == 4
    blocked = pool.acquire(size=1, job=8, timeout_s=0.05)
    assert blocked is None
    pool.release(lease)

    evs = _events(sink)
    by_name = {e["ev"]: e for e in evs}
    grant = by_name["lease_grant"]
    assert grant["job"] == 7
    assert grant["size"] == 4
    assert grant["devices"] == [0, 1, 2, 3]
    assert grant["in_use"] == 4
    timeout = by_name["lease_timeout"]
    assert timeout["job"] == 8
    release = by_name["lease_release"]
    assert release["in_use"] == 0
    assert release["held_s"] >= 0

    snap = stats.snapshot()
    assert snap["leases_granted"] == 1
    assert snap["lease_timeouts"] == 1
    rendered = stats.registry.render()
    assert "verifyd_leases_granted_total 1" in rendered
    assert "verifyd_lease_timeouts_total 1" in rendered
    assert "verifyd_devices_leased 0" in rendered  # released at the end
    assert "verifyd_lease_wait_seconds_count 1" in rendered
