"""The driver contract of bench.py: exactly one machine-readable JSON
line on stdout, with a ``backend`` provenance marker, in every outcome —
clean measurement, mid-run hang, and mid-run crash (the axon worker has
died mid-measurement in practice; the driver must get a parseable line
regardless).  Tiny instance sizes keep these subprocess-driven."""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = {
    "S2VTPU_BENCH_CLIENTS": "2",
    "S2VTPU_BENCH_OPS": "40",
    "S2VTPU_BENCH_ORACLE_BUDGET_S": "5",
    "S2VTPU_BENCH_SKIP_ADV": "1",
    # The suite pins JAX_PLATFORMS=cpu (conftest); children re-pin via the
    # config API, so everything below measures host cores.
}


def _run_bench(extra_env: dict[str, str], timeout: float = 300.0):
    env = dict(os.environ) | FAST | extra_env
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        timeout=timeout,
        cwd=REPO,
    )
    lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
    metric_lines = [l for l in lines if '"metric"' in l]
    assert len(metric_lines) == 1, (proc.stdout, proc.stderr[-2000:])
    return proc, json.loads(metric_lines[0])


def test_bench_clean_run_contract():
    proc, line = _run_bench({})
    assert proc.returncode == 0
    assert line["metric"] == "ops_verified_per_sec_chip"
    assert line["value"] > 0
    assert line["backend"] == "cpu"
    assert line["unit"] == "ops/s"


def test_bench_adversarial_line_carries_backend_provenance():
    """The stderr adversarial metric line must carry the same
    machine-readable backend marker as the headline — a host-cores
    number must never pass as an on-chip measurement (the r3 artifact
    did exactly that for this line)."""
    proc, line = _run_bench(
        {
            "S2VTPU_BENCH_SKIP_ADV": "0",
            "S2VTPU_BENCH_ADV_K": "6",
            "S2VTPU_BENCH_ADV_BATCH": "20",
            "S2VTPU_BENCH_ADV_NATIVE_BUDGET_S": "0",
        },
        timeout=600.0,
    )
    assert proc.returncode == 0
    adv = [
        json.loads(l)
        for l in proc.stderr.decode().splitlines()
        if '"metric"' in l and "adversarial" in l
    ]
    assert len(adv) == 1, proc.stderr[-2000:]
    assert adv[0]["metric"] == "adversarial_k6_device_wall_s"
    assert adv[0]["value"] > 0
    assert adv[0]["backend"] == "cpu"


def test_bench_midrun_hang_degrades_with_contract_line():
    # A 1-second measurement budget guarantees the child is killed mid-run;
    # NO_FALLBACK turns the degradation into the explicit zero line.
    proc, line = _run_bench(
        {"S2VTPU_BENCH_TPU_TIMEOUT_S": "1", "S2VTPU_BENCH_NO_FALLBACK": "1"}
    )
    assert proc.returncode == 1
    assert line["value"] == 0.0
    assert line["backend"] == "none"
    assert b"hung" in proc.stderr


def test_bench_midrun_crash_detected_with_contract_line():
    # A poisoned env var crashes the measurement child after the probe;
    # the parent must detect it and still print the contract line.
    env = dict(os.environ) | FAST | {"S2VTPU_BENCH_OPS": "bogus"}
    # The fallback child re-reads S2VTPU_BENCH_OPS, so poison only the
    # isolated child via a var the fallback corrects: use NO_FALLBACK to
    # assert the crash detection itself instead.
    env["S2VTPU_BENCH_NO_FALLBACK"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        timeout=300,
        cwd=REPO,
    )
    lines = [l for l in proc.stdout.decode().splitlines() if '"metric"' in l]
    assert len(lines) == 1
    line = json.loads(lines[0])
    assert line["value"] == 0.0 and line["backend"] == "none"
    assert b"child died" in proc.stderr
