"""Differential tests: device frontier search vs the host engines.

Runs on the virtual 8-device CPU mesh (conftest.py); the same code path runs
unchanged on real TPU chips.
"""

import random

import jax
import pytest

from helpers import H, fold
from helpers import assert_valid_linearization as _assert_valid_linearization
from s2_verification_tpu.checker.device import (
    check_device,
    check_device_auto,
    place_frontier,
)
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.frontier import check_frontier
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.collector.collect import CollectConfig, collect_history
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from test_oracle_bruteforce import random_history


def test_device_matches_dfs_on_random_histories():
    rng = random.Random(0xDEC0)
    for trial in range(60):
        h = random_history(rng)
        hist = prepare(h.events)
        want = check(hist)
        got = check_device(hist, max_frontier=256, start_frontier=16, beam=False)
        assert got.outcome == want.outcome, f"trial {trial}"
        if want.ok:
            assert got.final_states, f"trial {trial}"
            # Engines may surface different accepting linearizations; only a
            # history with no ambiguous appends has a unique final state.
            if not any(op.is_indefinite_append for op in hist.ops):
                assert sorted(got.final_states) == sorted(want.final_states), (
                    f"trial {trial}"
                )


def test_device_beam_matches_on_random_histories():
    rng = random.Random(0xBEA3)
    for trial in range(40):
        h = random_history(rng)
        hist = prepare(h.events)
        want = check(hist).outcome
        got = check_device(hist, max_frontier=256, start_frontier=64, beam=True).outcome
        # Beam OK/ILLEGAL-without-pruning are conclusive; UNKNOWN allowed.
        if got != CheckOutcome.UNKNOWN:
            assert got == want, f"trial {trial}"


@pytest.mark.parametrize("workflow", ["regular", "match-seq-num", "fencing"])
def test_device_on_collected_histories(workflow):
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=25,
            workflow=workflow,
            seed=7,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.3, max_latency=0.001),
        )
    )
    hist = prepare(events)
    res = check_device_auto(hist, beam_width=512, collect_stats=True)
    assert res.outcome == CheckOutcome.OK
    host = check_frontier(hist)
    assert host.outcome == CheckOutcome.OK


def test_device_rejects_corrupted_history():
    from s2_verification_tpu.utils.events import LabeledEvent, ReadSuccess

    events = collect_history(
        CollectConfig(
            num_concurrent_clients=3,
            num_ops_per_client=15,
            workflow="regular",
            seed=3,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.2, max_latency=0.001),
        )
    )
    tampered = []
    done = False
    for e in events:
        if not done and isinstance(e.event, ReadSuccess) and e.event.tail > 0:
            e = LabeledEvent(
                ReadSuccess(tail=e.event.tail, stream_hash=e.event.stream_hash ^ 1),
                e.client_id,
                e.op_id,
            )
            done = True
        tampered.append(e)
    assert done
    hist = prepare(tampered)
    assert check_device(hist, beam=False).outcome == CheckOutcome.ILLEGAL


def test_device_auto_close_keeps_frontier_narrow():
    h = H()
    tail, acc = 0, 0
    for i in range(3):
        rh = 200 + i
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    for i in range(10):
        h.call_append(100 + i, [i + 1], match=i % 3)  # dead open guards
    for i in range(20):
        rh = 50 + i
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    h.read_ok(2, tail=tail, stream_hash=acc)
    hist = prepare(h.events)
    res = check_device(hist, start_frontier=16, beam=False, collect_stats=True)
    assert res.outcome == CheckOutcome.OK
    assert res.stats.auto_closed >= 10
    assert res.stats.max_frontier <= 8


def test_device_state_slot_escalation():
    # k live unguarded opens before a pinning read: state sets reach 2^k
    # members, overflowing the starting slot bucket; the driver must regrow
    # and still conclude OK.
    h = H()
    k = 4
    opens = []
    for i in range(k):
        opens.append(h.call_append(10 + i, [i + 1]))
    tail, acc = 0, 0
    for i in range(3):
        rh = 90 + i
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    h.read_ok(2, tail=tail, stream_hash=acc)  # pins: no open took effect
    hist = prepare(h.events)
    res = check_device(hist, state_slots=2, start_frontier=16, beam=False, collect_stats=True)
    want = check(hist)
    assert res.outcome == want.outcome == CheckOutcome.OK


def test_device_frontier_escalation_exhaustive():
    # Live ambiguity wider than the starting bucket: exhaustive mode must
    # escalate the frontier and still match the oracle.
    h = H()
    for i in range(6):
        h.call_append(10 + i, [i + 1])
    h.append_ok(1, [99], tail=1)
    hist = prepare(h.events)
    res = check_device(hist, start_frontier=2, max_frontier=256, state_slots=2, beam=False)
    assert res.outcome == check(hist).outcome


def test_device_sharded_over_mesh():
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provide the virtual 8-device mesh"
    mesh = Mesh(devices[:8], ("fr",))
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=20,
            workflow="match-seq-num",
            seed=11,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.3, max_latency=0.001),
        )
    )
    hist = prepare(events)
    res = check_device(hist, start_frontier=64, mesh=mesh, beam=False)
    assert res.outcome == CheckOutcome.OK


def test_device_empty_history():
    hist = prepare([])
    assert check_device(hist).outcome == CheckOutcome.OK


def test_device_witness_on_random_histories():
    # The accept-path witness must be a genuine linearization — validated
    # independently (coverage, real-time order, non-empty state sets) — the
    # analog of CheckEventsVerbose's linearization info (main.go:605-631).
    rng = random.Random(0x717)
    checked = 0
    for trial in range(40):
        h = random_history(rng)
        hist = prepare(h.events)
        got = check_device(hist, max_frontier=256, start_frontier=16, beam=False)
        if got.outcome == CheckOutcome.OK:
            assert got.linearization is not None, f"trial {trial}"
            _assert_valid_linearization(hist, got.linearization)
            checked += 1
    assert checked >= 5


@pytest.mark.parametrize("workflow", ["regular", "match-seq-num", "fencing"])
def test_device_witness_on_collected_histories(workflow):
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=15,
            workflow=workflow,
            seed=23,
            faults=FaultPlan.chaos(0.25),
        )
    )
    hist = prepare(events)
    # start_frontier=2 forces capacity escalations mid-run, exercising the
    # witness log across segment boundaries and _regrow row preservation
    # (witness_max_frontier>0 opts into the device log path; the default
    # is counts-bounded recovery, covered by the other witness tests).
    res = check_device(
        hist, max_frontier=4096, start_frontier=2, beam=False,
        witness_max_frontier=4096,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.linearization is not None
    _assert_valid_linearization(hist, res.linearization)


def test_device_witness_adversarial():
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=4, seed=1))
    res = check_device(hist, max_frontier=4096, start_frontier=16, beam=False)
    assert res.outcome == CheckOutcome.OK
    assert res.linearization is not None
    _assert_valid_linearization(hist, res.linearization)


def test_device_witness_recovered_beyond_cap():
    # Past witness_max_frontier the per-layer log is dropped, but an OK
    # verdict now recovers a witness via the counts-bounded host re-search
    # (VERDICT r2 #2) — the regime the chip exists for must not produce a
    # poorer artifact than the reference's Visualize info (main.go:605-631).
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=4, seed=1))
    res = check_device(
        hist, max_frontier=4096, start_frontier=16, beam=False,
        witness_max_frontier=16,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.linearization is not None
    _assert_valid_linearization(hist, res.linearization)


def test_device_witness_off_means_off():
    # witness=False is a caller choice: no log, no recovery.
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=4, seed=1))
    res = check_device(
        hist, max_frontier=4096, start_frontier=16, beam=False,
        witness=False,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.linearization is None


def test_spill_witness_recovered():
    # The witness log cannot survive the out-of-core spill; the recovered
    # linearization must still validate independently.
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(6, batch=4, seed=1))
    res = check_device(
        hist, max_frontier=32, start_frontier=32, beam=False, spill=True,
        collect_stats=True,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.stats.max_frontier > 32
    assert res.linearization is not None
    _assert_valid_linearization(hist, res.linearization)


def test_refusals_survive_fast_stretch_death():
    # Regression: a row that dies mid-stretch inside the multi-op fast
    # layer (_fast_multi) must yield refusal diagnostics at the DEATH
    # POINT, not at the stretch entry.  Shape: brief concurrency (a
    # returned ambiguous append, pinned by a check-tail) collapsing to a
    # single row, then a forced sequential stretch of successful appends
    # ending in a read whose hash is corrupted — the read must be named.
    from s2_verification_tpu.utils import events as ev
    from s2_verification_tpu.utils.hashing import fold_record_hashes

    events = [
        ev.LabeledEvent(
            ev.AppendStart(num_records=1, record_hashes=(11,)),
            client_id=1,
            op_id=0,
        ),
        ev.LabeledEvent(ev.AppendIndefiniteFailure(), client_id=1, op_id=0),
        ev.LabeledEvent(ev.CheckTailStart(), client_id=2, op_id=1),
        ev.LabeledEvent(ev.CheckTailSuccess(tail=1), client_id=2, op_id=1),
    ]
    h = fold_record_hashes(0, [11])
    for i in range(6):
        events.append(
            ev.LabeledEvent(
                ev.AppendStart(num_records=1, record_hashes=(100 + i,)),
                client_id=3,
                op_id=2 + i,
            )
        )
        events.append(
            ev.LabeledEvent(ev.AppendSuccess(tail=2 + i), client_id=3, op_id=2 + i)
        )
        h = fold_record_hashes(h, [100 + i])
    events.append(ev.LabeledEvent(ev.ReadStart(), client_id=3, op_id=8))
    events.append(
        ev.LabeledEvent(
            ev.ReadSuccess(tail=7, stream_hash=h ^ 1), client_id=3, op_id=8
        )
    )
    hist = prepare(events)
    res = check_device(hist, max_frontier=64, start_frontier=16, beam=False)
    assert res.outcome == CheckOutcome.ILLEGAL
    read_idx = {i for i, o in enumerate(hist.ops) if o.inp.input_type == 1}
    assert res.refusals, "no refusal report after a fast-stretch death"
    assert any(read_idx & set(refused) for _, refused in res.refusals), (
        f"culprit read not named: {res.refusals}"
    )
    # The deepest prefix must reach the death point (everything but the read).
    assert max(len(p) for p, _ in res.refusals) == len(hist.ops) - 1


def test_spill_matches_oracle_on_random_histories():
    # Out-of-core mode: a tiny device bucket forces the frontier to spill
    # to host RAM and stream slabs; verdicts must still match the DFS and
    # stay conclusive (nothing is pruned).
    # (random_history instances are 1-4 ops, so most stay in-core; the
    # engagement proof lives in test_spill_adversarial_conclusive.)
    rng = random.Random(0x5B1)
    for trial in range(30):
        h = random_history(rng)
        hist = prepare(h.events)
        want = check(hist)
        got = check_device(
            hist, max_frontier=4, start_frontier=4, beam=False, spill=True,
        )
        assert got.outcome == want.outcome, f"trial {trial}"

    # A collected history through a bucket far below its frontier peak:
    # the whole mid-game runs out-of-core and must still accept.
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=12,
            workflow="match-seq-num",
            seed=31,
            faults=FaultPlan.chaos(0.3),
        )
    )
    hist = prepare(events)
    want = check(hist)
    got = check_device(
        hist, max_frontier=8, start_frontier=8, beam=False, spill=True
    )
    assert got.outcome == want.outcome


def test_spill_adversarial_conclusive():
    from s2_verification_tpu.collector.adversarial import adversarial_events

    # OK instance: spill must find the accept.
    hist = prepare(adversarial_events(6, batch=4, seed=1))
    res = check_device(
        hist, max_frontier=32, start_frontier=32, beam=False, spill=True,
        collect_stats=True,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.stats.max_frontier > 32  # genuinely out-of-core

    # Unsatisfiable instance: ILLEGAL by exhaustion, through the spill.
    hist = prepare(adversarial_events(5, batch=4, seed=2, unsatisfiable=True))
    res = check_device(
        hist, max_frontier=32, start_frontier=32, beam=False, spill=True
    )
    assert res.outcome == CheckOutcome.ILLEGAL
    assert res.deepest  # diagnostics survive the spill
    # Refusal reports survive the spill too, and the corrupted pinning
    # read is named as a culprit at some deepest configuration.
    assert res.refusals
    read_idx = [i for i, o in enumerate(hist.ops) if o.inp.input_type == 1]
    assert any(
        set(read_idx) & set(refused) for _, refused in res.refusals
    )


def test_device_refusals_name_the_culprit():
    # VERDICT r2 #5: on ILLEGAL, the device engine reports the deepest
    # configurations' refusing ops (per distinct counts signature), and
    # the corrupted pinning read is among them.
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(4, batch=4, seed=3, unsatisfiable=True))
    res = check_device(
        hist, max_frontier=4096, start_frontier=16, beam=False, witness=False
    )
    assert res.outcome == CheckOutcome.ILLEGAL
    assert res.refusals
    read_idx = {i for i, o in enumerate(hist.ops) if o.inp.input_type == 1}
    assert any(read_idx & set(refused) for _, refused in res.refusals)
    # Each report's prefix is sane: a subset of ops, disjoint from refused.
    for prefix, refused in res.refusals:
        assert set(prefix).isdisjoint(refused)
        assert all(0 <= i < len(hist.ops) for i in prefix + refused)


def test_spill_final_states_match_incore():
    # VERDICT r2 #4: a spill OK must report the same accept-configuration
    # candidate-state set as the in-core search — unioned across every slab
    # of the accept layer, not just the slab that accepted first.
    #
    # The adversarial family's accept set is provably a singleton (the
    # pinning read determines the state), so graft on two RETURNED
    # ambiguous appends (hashes X / Y) followed by a CheckTailSuccess
    # whose call opens after both finishes: real-time order forces both
    # appends into every accept configuration, and the check-tail pins
    # only the TAIL (exactly one of the two applied) — so the branch-swap
    # rows (X-applied vs Y-applied) share the accept counts with
    # different stream hashes: a genuine 2-state accept set.
    from s2_verification_tpu.collector.adversarial import adversarial_events
    from s2_verification_tpu.utils import events as ev

    k = 6
    batch, applied = 4, 3
    base = adversarial_events(k, batch=batch, seed=1, applied=applied)
    deferred, events = base[-k:], base[:-k]
    for j, h in enumerate((0xDEADBEEF, 0xCAFEF00D)):
        events.append(
            ev.LabeledEvent(
                ev.AppendStart(num_records=1, record_hashes=(h,)),
                client_id=k + 2 + j,
                op_id=k + 1 + j,
            )
        )
        events.append(
            ev.LabeledEvent(
                ev.AppendIndefiniteFailure(),
                client_id=k + 2 + j,
                op_id=k + 1 + j,
            )
        )
    events.append(
        ev.LabeledEvent(ev.CheckTailStart(), client_id=k + 4, op_id=k + 3)
    )
    events.append(
        ev.LabeledEvent(
            ev.CheckTailSuccess(tail=applied * batch + 1),
            client_id=k + 4,
            op_id=k + 3,
        )
    )
    hist = prepare(events + deferred)

    incore = check_device(
        hist, max_frontier=1 << 13, start_frontier=1 << 13, beam=False,
        witness=False,
    )
    spilled = check_device(
        hist, max_frontier=32, start_frontier=32, beam=False, spill=True,
        collect_stats=True,
    )
    assert incore.outcome == CheckOutcome.OK
    assert spilled.outcome == CheckOutcome.OK
    assert spilled.stats.max_frontier > 32  # genuinely out-of-core
    assert len(incore.final_states) > 1  # the set is non-trivial
    assert spilled.final_states == incore.final_states


def test_spill_host_cap_gives_unknown():
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(7, batch=4, seed=3))
    res = check_device(
        hist, max_frontier=16, start_frontier=16, beam=False, spill=True,
        spill_host_cap=64,
    )
    assert res.outcome == CheckOutcome.UNKNOWN


def test_spill_sharded_over_mesh():
    # Out-of-core slabs placed on a sharded mesh: verdict must match.
    import numpy as np
    from jax.sharding import Mesh

    from s2_verification_tpu.collector.adversarial import adversarial_events

    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual mesh"
    hist = prepare(adversarial_events(6, batch=4, seed=1))
    mesh = Mesh(np.asarray(devices[:8]), ("fr",))
    res = check_device(
        hist, max_frontier=32, start_frontier=32, beam=False, spill=True,
        mesh=mesh, collect_stats=True,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.stats.max_frontier > 32


def test_dedup_rows_matches_np_unique():
    import numpy as np

    from s2_verification_tpu.checker.device import _dedup_rows

    rng = np.random.default_rng(7)
    for trial in range(20):
        n = int(rng.integers(1, 400))
        c = int(rng.integers(2, 8))
        # Low-cardinality values plant plenty of genuine duplicate rows.
        mat = rng.integers(-3, 3, (n, c)).astype(np.int32)
        want = np.unique(mat, axis=0)
        for bits in (64, 8, 2, 1):
            got = _dedup_rows(mat.copy(), _key_bits=bits)
            got = np.unique(got, axis=0)  # canonical order for comparison
            assert got.shape == want.shape, (trial, bits)
            assert (got == want).all(), (trial, bits)


def test_dedup_rows_collision_separated_duplicates():
    # The fixup-partition regression: equal rows separated inside one hash
    # run (forced by a 0-bit-entropy key) must not survive in duplicate.
    import numpy as np

    from s2_verification_tpu.checker.device import _dedup_rows

    a = np.array([1, 2, 3], np.int32)
    b = np.array([4, 5, 6], np.int32)
    mat = np.stack([a, a, b, a, b, b, a])
    got = _dedup_rows(mat, _key_bits=1)
    got = np.unique(got, axis=0)
    want = np.unique(mat, axis=0)
    assert (got == want).all()


def test_driver_fetches_stay_small(monkeypatch):
    # Transfer-discipline regression guard: with witnessing off, the
    # driver's happy path must fetch only steering scalars, the [C]
    # deep-counts row, and the compacted accept set (host<->device traffic
    # was the k>=10 bottleneck through the tunnel; on any hardware it is
    # waste).  The driver fetches exclusively through the module-level
    # aliases D.device_get / D.asarray, so patching those module
    # attributes spies on exactly this module's fetch surface — other
    # callers in the process (parallel tests, jax internals) are
    # untouched, and monkeypatch restores them exception-safely.
    import s2_verification_tpu.checker.device as D
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=10, seed=2))
    fetched: list[int] = []
    real_get = D.device_get
    real_asarray = D.asarray

    def record(x):
        for leaf in jax.tree.leaves(x):
            if isinstance(leaf, jax.Array):
                fetched.append(int(leaf.size))

    def spy_get(x):
        record(x)
        return real_get(x)

    def spy_asarray(x, *a, **k):
        record(x)
        return real_asarray(x, *a, **k)

    monkeypatch.setattr(D, "device_get", spy_get)
    monkeypatch.setattr(D, "asarray", spy_asarray)
    res = D.check_device(
        hist, max_frontier=4096, start_frontier=16, beam=False,
        witness=False,
    )
    assert res.outcome == CheckOutcome.OK
    assert fetched, "spy saw no fetches"
    # This search escalates through a few-hundred-row frontier; every
    # legal fetch above is far smaller still.  A regression that pulls any
    # whole frontier column (or the counts matrix) exceeds this at once.
    assert max(fetched) <= 64, f"oversized device fetch: {max(fetched)}"


def test_witness_recovery_budget_exhaustion_omits_witness():
    # The counts-bounded recovery is best-effort: an exhausted node budget
    # omits the witness (verdict-only result), never wedges or raises.
    import s2_verification_tpu.checker.device as D
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=4, seed=1))
    enc = D.encode_history(hist)
    # Recover normally first to obtain the accept counts via a real run.
    res = check_device(hist, max_frontier=4096, start_frontier=16, beam=False)
    assert res.outcome == CheckOutcome.OK and res.linearization is not None
    # Derive the accept counts from the witness itself.
    import numpy as np

    ki = enc.keep_index()
    pos = {j: i for i, j in enumerate(ki)}
    counts = np.array(enc.chain_start, np.int64)
    lin_encoded = [pos[i] for i in res.linearization if i in pos]
    target = counts.copy()
    for j in lin_encoded:
        target[int(enc.chain_of[j])] += 1
    got = D._recover_witness_bounded(enc, hist, target, node_budget=2)
    assert got is None  # budget too small -> omitted, no exception
    got = D._recover_witness_bounded(enc, hist, target)
    assert got is not None  # default budget succeeds on the same input


def test_pack_strides_exactness_boundary():
    """Stride math and the 2^64 exactness cutoff (pure host arithmetic)."""
    import numpy as np

    from s2_verification_tpu.checker.device import _pack_strides

    exact, strides = _pack_strides(np.array([3, 1, 2], np.int32))
    assert exact
    # Mixed-radix: stride[0]=1, stride[1]=4 (radix 3+1), stride[2]=4*2.
    assert strides.tolist() == [1, 4, 8]
    # 8 chains of radix 256 multiply to exactly 2^64: every key fits u64.
    exact, _ = _pack_strides(np.full(8, 255, np.int32))
    assert exact
    # One more value overflows: keys would alias, so packing is refused.
    exact, _ = _pack_strides(np.array([255] * 8 + [1], np.int32))
    assert not exact


def test_device_packed_vs_generic_dedup_differential():
    """exact_pack=True and =False must agree on verdict, witness validity,
    final states, and the search shape (layers/expansions)."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    for k, unsat in ((5, False), (5, True), (6, False)):
        hist = prepare(adversarial_events(k, batch=4, seed=2, unsatisfiable=unsat))
        runs = {}
        for xp in (True, False):
            r = check_device(
                hist,
                max_frontier=4096,
                start_frontier=16,
                beam=False,
                collect_stats=True,
                exact_pack=xp,
            )
            runs[xp] = r
        a, b = runs[True], runs[False]
        assert a.outcome == b.outcome
        if a.outcome == CheckOutcome.OK:
            assert sorted(a.final_states) == sorted(b.final_states)
            _assert_valid_linearization(hist, a.linearization)
            _assert_valid_linearization(hist, b.linearization)
        assert a.stats.layers == b.stats.layers
        assert a.stats.expanded == b.stats.expanded


def test_spill_packed_dedup_conclusive():
    """The packed key flows through the out-of-core spill path too."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(6, batch=4, seed=1))
    r = check_device(
        hist,
        max_frontier=64,
        start_frontier=16,
        beam=False,
        spill=True,
        exact_pack=True,
        collect_stats=True,
    )
    assert r.outcome == CheckOutcome.OK
    _assert_valid_linearization(hist, r.linearization)


def test_exact_pack_refused_when_unpackable():
    """Forcing exact_pack on a counts space wider than u64 must raise, not
    silently alias keys (zeroed strides would merge distinct configs)."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    # 65 single-op append chains + the read chain: product 2^66 > 2^64.
    hist = prepare(adversarial_events(65, batch=1, seed=0))
    from s2_verification_tpu.checker.device import can_exact_pack
    from s2_verification_tpu.models.encode import encode_history

    assert not can_exact_pack(encode_history(hist))
    with pytest.raises(ValueError, match="exact_pack"):
        check_device(hist, max_frontier=64, start_frontier=16, exact_pack=True)


def test_device_sort_dedup_differential():
    """Sort-based and scatter-based dedup must agree on verdict, witness,
    final states, and layer count (expansions can differ only if the probe
    table ever missed a merge; on these sizes it does not)."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    for k, unsat in ((5, False), (5, True), (6, False)):
        hist = prepare(adversarial_events(k, batch=4, seed=3, unsatisfiable=unsat))
        runs = {}
        for sd in (True, False):
            runs[sd] = check_device(
                hist,
                max_frontier=4096,
                start_frontier=16,
                beam=False,
                collect_stats=True,
                sort_dedup=sd,
            )
        a, b = runs[True], runs[False]
        assert a.outcome == b.outcome
        if a.outcome == CheckOutcome.OK:
            assert sorted(a.final_states) == sorted(b.final_states)
            _assert_valid_linearization(hist, a.linearization)
        assert a.stats.layers == b.stats.layers
        assert a.stats.expanded == b.stats.expanded


def test_device_sort_dedup_on_collected_history_and_spill():
    """The sort path decides a real collected history and flows through
    the out-of-core spill."""
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=3,
            num_ops_per_client=30,
            workflow="fencing",
            seed=11,
        )
    )
    hist = prepare(events)
    r = check_device(hist, max_frontier=4096, start_frontier=16, sort_dedup=True)
    assert r.outcome == CheckOutcome.OK
    _assert_valid_linearization(hist, r.linearization)

    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(6, batch=4, seed=1))
    r = check_device(
        hist,
        max_frontier=64,
        start_frontier=16,
        beam=False,
        spill=True,
        sort_dedup=True,
    )
    assert r.outcome == CheckOutcome.OK
    _assert_valid_linearization(hist, r.linearization)


def test_sort_dedup_refused_when_unpackable():
    """Explicit sort_dedup=True without the packed key must refuse (env
    opt-in degrades instead; the explicit flag is an experiment contract)."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(65, batch=1, seed=0))
    with pytest.raises(ValueError, match="sort_dedup"):
        check_device(hist, max_frontier=64, start_frontier=16, sort_dedup=True)


def test_chunked_big_frontier_differential():
    """The HBM-resident chunked tier (device_rows_cap > max_frontier) must
    match the one-shot in-core search exactly: verdicts, layers,
    expansions, peak, witness validity — on OK, ILLEGAL-by-exhaustion,
    and a case whose peak exceeds the expansion bucket many times over."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    for k, unsat in ((6, False), (6, True), (5, False)):
        hist = prepare(adversarial_events(k, batch=4, seed=1, unsatisfiable=unsat))
        # sort_dedup on the reference too: the probe table may keep a
        # hash-colliding duplicate ("a missed merge wastes a row"), which
        # would make the exact stats equality below spuriously fail; both
        # sides on the perfect sort dedup makes it exact by construction.
        ref = check_device(
            hist, max_frontier=4096, start_frontier=16, beam=False,
            collect_stats=True, sort_dedup=True,
        )
        big = check_device(
            hist, max_frontier=64, start_frontier=16, beam=False,
            device_rows_cap=4096, collect_stats=True,
        )
        assert big.outcome == ref.outcome
        assert big.stats.layers == ref.stats.layers
        assert big.stats.expanded == ref.stats.expanded
        assert big.stats.max_frontier == ref.stats.max_frontier
        if ref.outcome == CheckOutcome.OK:
            assert sorted(big.final_states) == sorted(ref.final_states)
            _assert_valid_linearization(hist, big.linearization)


def test_chunked_tier_hands_off_to_spill_past_device_cap():
    """Past device_rows_cap the search must still not concede: with
    spill=True it hands off to the host tier and stays conclusive."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(6, batch=4, seed=1))
    r = check_device(
        hist, max_frontier=32, start_frontier=16, beam=False,
        device_rows_cap=128, spill=True, collect_stats=True,
    )
    assert r.outcome == CheckOutcome.OK
    _assert_valid_linearization(hist, r.linearization)


def test_chunked_tier_gated_off_for_beam_and_unpackable():
    """Beam runs and unpackable histories never enter the chunked tier:
    beam prunes at the bucket; unpackable lacks the identity key."""
    from s2_verification_tpu.collector.adversarial import adversarial_events

    hist = prepare(adversarial_events(5, batch=4, seed=1))
    r = check_device(
        hist, max_frontier=64, start_frontier=16, beam=True,
        device_rows_cap=4096, collect_stats=True,
    )
    # Beam at a tiny bucket prunes; verdict is OK (conclusive) or UNKNOWN,
    # never an error from the chunked assert.
    assert r.outcome in (CheckOutcome.OK, CheckOutcome.UNKNOWN)

    hist = prepare(adversarial_events(65, batch=1, seed=0))
    # Unpackable: device_rows_cap silently degrades to the plain bucket
    # cap; the run must not crash (UNKNOWN at cap is acceptable).
    r = check_device(
        hist, max_frontier=128, start_frontier=16, beam=False,
        device_rows_cap=512,
    )
    assert r.outcome in (CheckOutcome.OK, CheckOutcome.UNKNOWN)
