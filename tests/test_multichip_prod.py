"""Production-shape multi-chip correctness (VERDICT r4 #6).

The toy-shape sharded tests (test_device.py) prove the mesh path compiles
and agrees at small widths; these prove it at the scale the chip will
actually see: the k=10-class adversarial instance whose frontier peaks at
410 971 rows (>= 2^18) — the same state space as the BASELINE.md headline
regime (batch=1 keeps the space identical and drops only fold cost,
BASELINE.md "Layer-cost apportionment") — composed with checkpoint
interrupt/resume, the HBM chunked tier, and the out-of-core spill.

Composition map (why each arm is shaped the way it is):

- DEFAULT (S2VTPU_PROD_MESH=1): one sharded arm — spill to host past the
  2^18 bucket, preempted by the spill host-row cap (UNKNOWN + snapshot),
  resumed from the snapshot under the mesh to the conclusive verdict,
  witness equality against the unsharded reference — plus the unsharded
  chunked-tier preempt/resume arm.  The chunked tier runs UNSHARDED by
  design: under a mesh it is deliberately disabled
  (checker/device.py:1581-1592) — sharding already divides the expansion
  working set per device, and chunk slices across the sharded frontier
  axis would force cross-shard gathers; aggregate-HBM growth comes from
  adding devices.  The sharded out-of-bucket production path is the
  spill.
- FULL (S2VTPU_PROD_MESH_FULL=1, additive): the KeyboardInterrupt
  preempt/resume variant of the sharded spill arm, and the fully
  in-bucket 2^19 arm (peak resident, no spill).  Every full sharded
  search at the 410k-row width costs ~8x-serialized execution per
  virtual device on a core-starved host (see conftest's Eigen guard) —
  the default suite runs two such searches, FULL adds four more.

The production-width arms are slow (tens of minutes on few cores):
opt-in via S2VTPU_PROD_MESH=1 (the ``_PROD_GATE`` mark); CI runs them as
their own step.  The mesh-SERVING tests at the end (daemon round-trip
parity, checkpoint resume across a device re-grant) run toy-width and
stay in tier-1.
"""

from __future__ import annotations

import os

import pytest

_PROD_GATE = pytest.mark.skipif(
    os.environ.get("S2VTPU_PROD_MESH") != "1",
    reason="production-shape mesh suite is opt-in: set S2VTPU_PROD_MESH=1",
)

import jax
import numpy as np

from helpers import assert_valid_linearization
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.oracle import CheckOutcome
from s2_verification_tpu.collector.adversarial import adversarial_events

K = 10
PEAK_ROWS = 410_971  # measured frontier peak of this instance (BASELINE.md)
BUCKET = 1 << 19  # in-bucket arm: peak fits (410 971 < 524 288)
SMALL_BUCKET = 1 << 18  # out-of-bucket arms: peak overflows (> 262 144)
START = 1 << 12
# Sharded arms start at the production bucket: every escalation level
# compiles its own GSPMD-partitioned program (minutes each on a small
# host), and the x4 ladder is already exercised sharded at toy widths
# (test_device.py).  What these arms add is the production WIDTH.
START_SHARDED = 1 << 18


@pytest.fixture(scope="module")
def hist():
    return prepare(adversarial_events(K, batch=1, seed=0))


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual 8-device mesh"
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices[:8]), ("fr",))


@pytest.fixture(scope="module")
def unsharded(hist):
    """Reference arm: one in-bucket exhaustive run, witness validated."""
    from s2_verification_tpu.checker.device import check_device

    res = check_device(
        hist,
        max_frontier=BUCKET,
        start_frontier=START,
        beam=False,
        collect_stats=True,
        witness=True,
    )
    assert res.outcome == CheckOutcome.OK
    assert res.stats.max_frontier >= 1 << 18, res.stats.max_frontier
    assert res.linearization is not None
    assert_valid_linearization(hist, res.linearization)
    return res


def _interrupt_after(n_calls: int):
    """Patch device.run_search to preempt after ``n_calls`` segments.

    check_device snapshots only after a segment RETURNS, so the preempt
    fires inside call ``n_calls`` — the snapshot on disk is then from
    call ``n_calls - 1`` (use n_calls >= 2 to guarantee one exists).
    """
    import s2_verification_tpu.checker.device as dev

    real_run = dev.run_search
    calls = {"n": 0}

    def interrupting(*a, **kw):
        calls["n"] += 1
        out = real_run(*a, **kw)
        if calls["n"] == n_calls:
            raise KeyboardInterrupt
        return out

    return real_run, interrupting


def _interrupt_when_snapshot_past(ck: str, threshold: int):
    """Patch device.run_search to preempt once the on-disk snapshot's
    frontier width exceeds ``threshold`` — call counts can't target the
    big tier robustly (escalation stops and per-layer segments both
    consume calls), but the snapshot width says exactly where we are."""
    import s2_verification_tpu.checker.device as dev
    from s2_verification_tpu.checker.checkpoint import load_checkpoint

    real_run = dev.run_search

    def interrupting(*a, **kw):
        if os.path.exists(ck) and load_checkpoint(ck).f > threshold:
            raise KeyboardInterrupt
        return real_run(*a, **kw)

    return real_run, interrupting


def _preempt_then_resume_sharded(
    hist, mesh, unsharded, ck: str, *, max_frontier: int, spill: bool,
    min_peak: int,
):
    """Shared preempt/resume harness for the sharded arms.

    Call 1 (2-layer segment at the starting bucket) returns and
    snapshots; the preempt fires inside call 2, leaving committed work
    to resume.  The resumed run must reproduce the unsharded reference:
    verdict, witness validity, and witness length (both linearizations
    place every op exactly once; order may differ)."""
    import s2_verification_tpu.checker.device as dev

    kw = dict(
        max_frontier=max_frontier,
        start_frontier=START_SHARDED,
        beam=False,
        mesh=mesh,
        spill=spill,
        witness=True,
    )
    real_run, interrupting = _interrupt_after(2)
    dev.run_search = interrupting
    try:
        with pytest.raises(KeyboardInterrupt):
            dev.check_device(
                hist, checkpoint_path=ck, checkpoint_every=2, **kw
            )
    finally:
        dev.run_search = real_run
    assert os.path.exists(ck)

    res = dev.check_device(
        hist,
        checkpoint_path=ck,
        checkpoint_every=64,
        collect_stats=True,
        **kw,
    )
    assert res.outcome == unsharded.outcome == CheckOutcome.OK
    # A conclusive verdict spends the snapshot(s).
    assert not os.path.exists(ck)
    assert not os.path.exists(ck + ".spill.npz")
    assert res.stats.max_frontier >= min_peak
    assert res.linearization is not None
    assert_valid_linearization(hist, res.linearization)
    assert len(res.linearization) == len(unsharded.linearization)


_FULL_GATE = pytest.mark.skipif(
    os.environ.get("S2VTPU_PROD_MESH") != "1"
    or os.environ.get("S2VTPU_PROD_MESH_FULL") != "1",
    reason="needs BOTH S2VTPU_PROD_MESH=1 and S2VTPU_PROD_MESH_FULL=1 "
    "(each extra full sharded search costs tens of minutes on few cores)",
)


@_FULL_GATE
def test_prodmesh_sharded_checkpoint_resume_matches_unsharded(
    hist, mesh, unsharded, tmp_path
):
    """Sharded run preempted mid-search (simulated preemption), resumed
    sharded: verdict + witness must match the unsharded reference at the
    410k-row production width.  FULL-gated: the default suite's spill-cap
    arm already covers sharded resume at this width with half the
    searches; this adds the KeyboardInterrupt-preempt path."""
    _preempt_then_resume_sharded(
        hist,
        mesh,
        unsharded,
        str(tmp_path / "prod.ckpt"),
        max_frontier=SMALL_BUCKET,
        spill=True,
        min_peak=1 << 18,
    )


@_PROD_GATE
def test_prodmesh_chunked_tier_checkpoint_resume(hist, unsharded, tmp_path):
    """HBM chunked tier at production width, preempted and resumed.

    Unsharded on purpose: the chunked tier is mesh-exclusive by design
    (checker/device.py:1581-1592) — see module docstring.
    """
    import s2_verification_tpu.checker.device as dev
    from s2_verification_tpu.checker.checkpoint import load_checkpoint

    ck = str(tmp_path / "chunk.ckpt")
    # Preempt at the first segment AFTER a snapshot from the big tier
    # (frontier wider than the expansion bucket) has landed on disk.
    real_run, interrupting = _interrupt_when_snapshot_past(ck, SMALL_BUCKET)
    dev.run_search = interrupting
    try:
        with pytest.raises(KeyboardInterrupt):
            dev.check_device(
                hist,
                max_frontier=SMALL_BUCKET,
                start_frontier=START,
                beam=False,
                device_rows_cap=1 << 19,
                checkpoint_path=ck,
                checkpoint_every=1,
                witness=True,
            )
    finally:
        dev.run_search = real_run
    assert os.path.exists(ck)
    saved = load_checkpoint(ck)
    assert saved.f > SMALL_BUCKET  # the snapshot is from the big tier

    res = dev.check_device(
        hist,
        max_frontier=SMALL_BUCKET,
        start_frontier=START,
        beam=False,
        device_rows_cap=1 << 19,
        checkpoint_path=ck,
        checkpoint_every=4,
        collect_stats=True,
        witness=True,
    )
    assert res.outcome == unsharded.outcome == CheckOutcome.OK
    assert res.stats.max_frontier >= 1 << 18
    assert res.linearization is not None
    assert_valid_linearization(hist, res.linearization)


@_FULL_GATE
def test_prodmesh_sharded_inbucket_full(hist, mesh, unsharded, tmp_path):
    """The whole 410k-row peak RESIDENT on the sharded mesh (no spill):
    the shape an 8-chip slice would run in-core.  The most expensive arm
    (widest sharded programs, no streaming) — FULL-gated."""
    _preempt_then_resume_sharded(
        hist,
        mesh,
        unsharded,
        str(tmp_path / "full.ckpt"),
        max_frontier=BUCKET,
        spill=False,
        min_peak=PEAK_ROWS,
    )


@_PROD_GATE
def test_prodmesh_sharded_spill_snapshot_resume(hist, mesh, unsharded, tmp_path):
    """The DEFAULT sharded production arm: spill to host RAM past the
    2^18 bucket, preempted by the host-row cap (UNKNOWN + snapshot on
    disk — a real mid-search interruption, no monkeypatching), resumed
    from the snapshot under the mesh to the conclusive verdict, witness
    checked against the unsharded reference.

    Cost note (measured round 5): on a 1-CORE host the two sharded
    searches exceed 3 h wall — 8 virtual devices serialized on one core.
    Budget ~25-45 min on a 4-core CI runner.  The same composition is
    validated at toy width every suite run (test_device.py
    test_spill_sharded_over_mesh, test_checkpoint.py
    test_spill_checkpoint_resume)."""
    from s2_verification_tpu.checker.device import check_device

    ck = str(tmp_path / "spill.ckpt")
    res = check_device(
        hist,
        max_frontier=SMALL_BUCKET,
        start_frontier=START_SHARDED,
        beam=False,
        mesh=mesh,
        spill=True,
        spill_host_cap=1 << 18,  # < 410k peak: forces the capped UNKNOWN
        checkpoint_path=ck,
        witness=True,
    )
    assert res.outcome == CheckOutcome.UNKNOWN
    assert os.path.exists(ck + ".spill.npz")

    res = check_device(
        hist,
        max_frontier=SMALL_BUCKET,
        start_frontier=START_SHARDED,
        beam=False,
        mesh=mesh,
        spill=True,
        spill_host_cap=1 << 26,
        checkpoint_path=ck,
        collect_stats=True,
        witness=True,
    )
    assert res.outcome == unsharded.outcome == CheckOutcome.OK
    assert not os.path.exists(ck + ".spill.npz")
    assert res.stats.max_frontier >= 1 << 18
    assert res.linearization is not None
    assert_valid_linearization(hist, res.linearization)
    # Both witnesses place every op exactly once; order may differ.
    assert len(res.linearization) == len(unsharded.linearization)


# -- mesh serving (toy width, un-gated: tier-1) ------------------------------


def test_mesh_daemon_roundtrip_sharded_vs_single(tmp_path, monkeypatch):
    """ISSUE 4 acceptance: a verifyd with an 8-device pool serves an
    adversarial history through the sharded escalation path and returns
    the same verdict as a 1-device daemon, reporting backend
    ``device-mesh[N]`` and populating the per-shard metric families.

    Inline escalation (the children-free path — the supervised child
    round-trip is `make mesh`); the CPU pass is stubbed to always return
    UNKNOWN so every submission deterministically escalates."""
    import io

    from s2_verification_tpu.checker.oracle import CheckResult
    from s2_verification_tpu.service import scheduler as sched_mod
    from s2_verification_tpu.service.client import VerifydClient
    from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
    from s2_verification_tpu.utils import events as ev

    monkeypatch.setattr(
        sched_mod,
        "_cpu_check",
        lambda h, budget, profile=False: (
            CheckResult(CheckOutcome.UNKNOWN),
            "native",
        ),
    )
    buf = io.StringIO()
    ev.write_history(adversarial_events(3, batch=2, seed=7), buf)
    text = buf.getvalue()

    answers = {}
    for n in (8, 1):
        cfg = VerifydConfig(
            socket_path=str(tmp_path / f"v{n}.sock"),
            device="inline",
            out_dir=str(tmp_path / f"viz{n}"),
            no_viz=True,
            stats_log=None,
            mesh_devices=n,
        )
        with Verifyd(cfg) as daemon:
            client = VerifydClient(cfg.socket_path)
            reply = client.submit(text, client="t")
            answers[n] = reply
            assert str(reply["backend"]).startswith("device-mesh["), reply
            snap = client.stats()
            assert snap["device_pool"]["total"] == n
            assert snap["device_pool"]["granted"] == 1
            assert snap["device_pool"]["in_use"] == 0  # released
            assert snap["leases_granted"] == 1
            if n == 8:
                rendered = daemon.registry.render()
                for fam in (
                    "verifyd_shard_frontier_occupancy",
                    "verifyd_shard_collective_seconds",
                    "verifyd_shard_skew",
                    "verifyd_leases_granted_total",
                    "verifyd_devices_leased",
                    "verifyd_lease_wait_seconds",
                ):
                    assert fam in rendered, f"missing family {fam}"
                # Genuinely sharded: more than one chip leased.
                assert reply["backend"] != "device-mesh[1]"

    assert answers[8]["verdict"] == answers[1]["verdict"]
    assert answers[8]["outcome"] == answers[1]["outcome"]
    assert answers[1]["backend"] == "device-mesh[1]"


def test_mesh_checkpoint_resume_across_regrant(tmp_path):
    """Checkpoint resume must survive a re-grant onto a *different* chip
    set: interrupt a search sharded over devices[:2], resume it sharded
    over devices[4:8] (disjoint set AND different size), and get the
    unmeshed verdict.  The shard summary must describe the new mesh."""
    import s2_verification_tpu.checker.device as dev
    from s2_verification_tpu.parallel.distributed import frontier_mesh

    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provision the virtual 8-device mesh"
    hist = prepare(adversarial_events(5, batch=4, seed=1))
    want = dev.check_device(hist, beam=False, max_frontier=256).outcome
    assert want == CheckOutcome.OK

    ck = str(tmp_path / "regrant.ckpt")
    real_run, interrupting = _interrupt_after(2)
    dev.run_search = interrupting
    try:
        with pytest.raises(KeyboardInterrupt):
            dev.check_device(
                hist,
                beam=False,
                max_frontier=256,
                mesh=frontier_mesh(devices=devices[:2]),
                checkpoint_path=ck,
                checkpoint_every=1,
            )
    finally:
        dev.run_search = real_run
    assert os.path.exists(ck)

    mesh_b = frontier_mesh(devices=devices[4:8])
    res = dev.check_device(
        hist,
        beam=False,
        max_frontier=256,
        mesh=mesh_b,
        checkpoint_path=ck,
        collect_stats=True,
    )
    assert res.outcome == want
    assert not os.path.exists(ck)  # conclusive verdict spends the snapshot
    shards = res.stats.shards
    assert len(shards) == 4  # the NEW mesh's shape, not the grantor's
    assert [e["device"] for e in shards] == [
        str(d) for d in mesh_b.devices.flat
    ]
    assert all(e["segments"] > 0 for e in shards)
