"""verifyd acceptance tests: daemon round trip, verdict cache, backpressure.

Everything runs under the session-wide ``JAX_PLATFORMS=cpu`` pin
(conftest.py) with device escalation off — the serving layer under test
is transport + admission + scheduling + caching, not the device search.
"""

import io
import json
import os
import socket as _socket
import subprocess
import sys
import time

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.cli import main as cli_main
from s2_verification_tpu.service.cache import VerdictCache, history_fingerprint
from s2_verification_tpu.service.client import (
    VerifydBusy,
    VerifydClient,
    VerifydError,
)
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.protocol import encode_frame
from s2_verification_tpu.service.queue import AdmissionQueue, Job, QueueFull
from s2_verification_tpu.service.scheduler import shape_key
from s2_verification_tpu.service.stats import ServiceStats
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

# -- fixtures ----------------------------------------------------------------


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history() -> str:
    """Linearizable: two clients, reads observe the folded appends."""
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    h.append_ok(2, [222, 333], tail=3)
    h.read_ok(1, tail=3, stream_hash=fold([111, 222, 333]))
    return _text(h)


def bad_history() -> str:
    """Non-linearizable: the read reports a stream hash no serialization
    of the appends can produce."""
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=12345)
    return _text(h)


def _write(tmp_path, name: str, text: str) -> str:
    p = tmp_path / name
    p.write_text(text, encoding="utf-8")
    return str(p)


def _daemon_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        out_dir=str(tmp_path / "viz"),
        stats_log=str(tmp_path / "stats.jsonl"),
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _events(tmp_path) -> list[dict]:
    with open(tmp_path / "stats.jsonl", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# -- the acceptance round trip ----------------------------------------------


def test_daemon_round_trip_matches_one_shot_cli(tmp_path):
    good, bad = good_history(), bad_history()
    good_path = _write(tmp_path, "good.jsonl", good)
    bad_path = _write(tmp_path, "bad.jsonl", bad)

    # Ground truth: the one-shot CLI's auto portfolio.
    one_shot_good = cli_main(["check", "-file", good_path, "-no-viz"])
    one_shot_bad = cli_main(["check", "-file", bad_path, "-no-viz"])
    assert (one_shot_good, one_shot_bad) == (0, 1)

    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)

        pong = client.ping()
        assert pong["server"] == "verifyd" and pong["protocol"] == 1

        r_good = client.submit(good, client="t", no_viz=True)
        r_bad = client.submit(bad, client="t", no_viz=True)
        # (a) daemon verdicts match the one-shot CLI exit codes
        assert r_good["verdict"] == one_shot_good
        assert r_bad["verdict"] == one_shot_bad
        assert r_good["outcome"] == "ok" and r_bad["outcome"] == "illegal"
        assert not r_good["cached"] and not r_bad["cached"]

        # (b) a duplicate is answered from the verdict cache
        r_dup = client.submit(good, client="t", no_viz=True)
        assert r_dup["verdict"] == one_shot_good
        assert r_dup["cached"] is True

        snap = client.stats()
        assert snap["submitted"] == 3
        assert snap["completed"] == 2
        assert snap["cache_hits"] == 1
        assert snap["cache_entries"] == 2

    events = _events(tmp_path)
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    # the cache hit is observable in the structured stats events
    assert len(by_ev["cache_hit"]) == 1
    hit = by_ev["cache_hit"][0]
    assert hit["fingerprint"] == history_fingerprint(
        prepare(list(ev.iter_history(good)), elide_trivial=True)
    )
    assert len(by_ev["done"]) == 2
    assert {e["verdict"] for e in by_ev["done"]} == {0, 1}
    assert by_ev["serve_stop"][0]["cache_hits"] == 1


def test_queue_full_rejected_with_backpressure_reply(tmp_path):
    # workers=0: nothing drains, so admission state is deterministic.
    cfg = _daemon_cfg(tmp_path, workers=0, queue_depth=1)
    with Verifyd(cfg) as daemon:
        # First job occupies the queue's single slot; submitted over a raw
        # socket whose reply we never await (no worker will resolve it).
        holder = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        holder.connect(cfg.socket_path)
        holder.sendall(
            encode_frame(
                {"op": "submit", "history": good_history(), "client": "hog"}
            )
        )
        deadline = time.monotonic() + 10
        while len(daemon.queue) < 1:
            assert time.monotonic() < deadline, "first job never admitted"
            time.sleep(0.01)

        # (c) the next submission is rejected immediately — a documented
        # backpressure reply with a retry hint, not a hang.
        client = VerifydClient(cfg.socket_path, timeout=10)
        with pytest.raises(VerifydBusy) as ei:
            client.submit(bad_history(), client="t")
        assert ei.value.cls == "QueueFull"
        assert ei.value.retry_after_s > 0
        assert ei.value.extra["depth"] == 1

        snap = client.stats()
        assert snap["rejected"] == 1
        holder.close()
    events = _events(tmp_path)
    rejects = [e for e in events if e["ev"] == "reject"]
    assert len(rejects) == 1 and rejects[0]["retry_after_s"] > 0


def test_submit_decode_error_and_artifact(tmp_path):
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        with pytest.raises(VerifydError) as ei:
            client.submit('{"not": "an event"}\n', client="t")
        assert ei.value.cls == "DecodeError"

        # default (no no_viz) writes the HTML artifact like one-shot check
        reply = client.submit(bad_history(), client="t")
        assert reply["artifact"] and os.path.exists(reply["artifact"])
        assert reply["artifact"].endswith(".html")
        assert os.path.dirname(reply["artifact"]) == str(tmp_path / "viz")


def test_in_flight_duplicate_answered_from_cache_at_execution(tmp_path):
    # Two identical jobs admitted before any worker runs: the second must
    # be answered by the execution-time cache check, not re-searched.
    cfg = _daemon_cfg(tmp_path, workers=0, queue_depth=8)
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path, timeout=120)
        socks = []
        for _ in range(2):
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.connect(cfg.socket_path)
            s.sendall(
                encode_frame(
                    {
                        "op": "submit",
                        "history": good_history(),
                        "client": "dup",
                        "no_viz": True,
                    }
                )
            )
            socks.append(s)
        deadline = time.monotonic() + 10
        while len(daemon.queue) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        daemon.scheduler.start(1)  # now let one worker drain both
        replies = []
        for s in socks:
            buf = b""
            s.settimeout(120)
            while not buf.endswith(b"\n"):
                chunk = s.recv(1 << 16)
                assert chunk, "daemon closed mid-reply"
                buf += chunk
            replies.append(json.loads(buf)["ok"])
            s.close()
        assert [r["verdict"] for r in replies] == [0, 0]
        assert sorted(r["cached"] for r in replies) == [False, True]
    hits = [e for e in _events(tmp_path) if e["ev"] == "cache_hit"]
    assert len(hits) == 1 and hits[0]["stage"] == "execute"


# -- CLI subcommands ---------------------------------------------------------


def test_serve_submit_cli_round_trip(tmp_path):
    sock = str(tmp_path / "verifyd.sock")
    good_path = _write(tmp_path, "good.jsonl", good_history())
    bad_path = _write(tmp_path, "bad.jsonl", bad_history())
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "s2_verification_tpu",
            "serve",
            "-socket",
            sock,
            "--device",
            "off",
            "-out-dir",
            str(tmp_path / "viz"),
            "--stats-log",
            str(tmp_path / "stats.jsonl"),
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        cwd=str(tmp_path),
    )
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(sock):
            assert proc.poll() is None, "serve exited early"
            assert time.monotonic() < deadline, "daemon socket never appeared"
            time.sleep(0.1)

        assert (
            cli_main(["submit", "-file", good_path, "-socket", sock, "-no-viz"])
            == 0
        )
        assert (
            cli_main(["submit", "-file", bad_path, "-socket", sock, "-no-viz"])
            == 1
        )
        # duplicate rides the verdict cache; -stats exposes it on stdout
        import contextlib

        cap = io.StringIO()
        with contextlib.redirect_stdout(cap):
            rc = cli_main(
                ["submit", "-file", good_path, "-socket", sock, "-no-viz", "-stats"]
            )
        assert rc == 0
        line = json.loads(cap.getvalue().strip())
        assert line["cached"] is True and line["outcome"] == "ok"

        # malformed history → usage exit from the daemon's decode reply
        junk = _write(tmp_path, "junk.jsonl", "{broken\n")
        assert cli_main(["submit", "-file", junk, "-socket", sock]) == 64

        VerifydClient(sock).shutdown()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_submit_without_daemon_is_unavailable(tmp_path):
    good_path = _write(tmp_path, "good.jsonl", good_history())
    rc = cli_main(
        ["submit", "-file", good_path, "-socket", str(tmp_path / "nope.sock")]
    )
    assert rc == 69  # EX_UNAVAILABLE


def test_serve_refuses_stale_socket(tmp_path):
    stale = tmp_path / "stale.sock"
    stale.write_text("")
    assert cli_main(["serve", "-socket", str(stale)]) == 64


# -- unit coverage: queue, cache, shapes ------------------------------------


def _job(i, priority=10, shape="4x2x1"):
    return Job(
        id=i,
        client="u",
        priority=priority,
        shape=shape,
        fingerprint=f"v1:{i:016x}:1",
        events=[],
        hist=None,
    )


def test_admission_queue_priority_and_shape_grouping():
    q = AdmissionQueue(depth=8, retry_hint=lambda d: 1.0)
    q.put(_job(1, priority=10, shape="A"))
    q.put(_job(2, priority=1, shape="B"))
    q.put(_job(3, priority=5, shape="B"))
    q.put(_job(4, priority=7, shape="A"))
    # Best-priority job leads; its shape-mates ride along in priority order.
    batch = q.get_batch(16, timeout=1)
    assert [j.id for j in batch] == [2, 3]
    batch = q.get_batch(16, timeout=1)
    assert [j.id for j in batch] == [4, 1]


def test_admission_queue_rejects_at_depth():
    q = AdmissionQueue(depth=2, retry_hint=lambda d: 2.5)
    q.put(_job(1))
    q.put(_job(2))
    with pytest.raises(QueueFull) as ei:
        q.put(_job(3))
    assert ei.value.depth == 2 and ei.value.retry_after_s == 2.5
    assert len(q) == 2  # reject means reject: nothing buffered past the bound


def test_fingerprint_stable_and_discriminating():
    g1 = prepare(list(ev.iter_history(good_history())), elide_trivial=True)
    g2 = prepare(list(ev.iter_history(good_history())), elide_trivial=True)
    b = prepare(list(ev.iter_history(bad_history())), elide_trivial=True)
    assert history_fingerprint(g1) == history_fingerprint(g2)
    assert history_fingerprint(g1) != history_fingerprint(b)
    assert history_fingerprint(g1).startswith("v2:")


def test_verdict_cache_lru_and_isolation():
    c = VerdictCache(capacity=2)
    c.put("a", {"verdict": 0})
    c.put("b", {"verdict": 1})
    got = c.get("a")
    got["verdict"] = 99  # caller mutation must not poison the cache
    assert c.get("a")["verdict"] == 0
    c.put("c", {"verdict": 2})  # evicts b (a was refreshed by the gets)
    assert c.get("b") is None and c.get("a") is not None


def test_shape_key_buckets_pad_like_the_encoder():
    small = prepare(list(ev.iter_history(good_history())), elide_trivial=True)
    assert shape_key(small) == "4x2x2"
    # same key for a same-bucket sibling: reuse of compiled executables
    h = H()
    h.append_ok(1, [5, 6], tail=2)
    h.read_ok(2, tail=2, stream_hash=fold([5, 6]))
    h.append_ok(2, [7], tail=3)
    sib = prepare(list(ev.iter_history(_text(h))), elide_trivial=True)
    assert shape_key(sib) == shape_key(small)


def test_stats_retry_hint_is_clamped():
    s = ServiceStats(None)
    assert s.retry_after_hint(0) == 0.5  # empty queue: floor, never "0"
    assert s.retry_after_hint(4) == 4.0  # cold daemon assumes 1s/job
    s.emit("done", wall_s=20.0, verdict=0)
    assert s.retry_after_hint(100) == 30.0  # depth x avg, ceiling


def test_stats_retry_hint_counts_in_flight_jobs():
    # A deep queue behind busy workers drains no faster than the workers
    # finish: jobs already handed to a worker (start without done) must
    # inflate the hint alongside queued depth.
    s = ServiceStats(None)
    s.emit("done", wall_s=2.0, verdict=0)  # avg wall = 2s
    assert s.retry_after_hint(1) == 2.0  # 1 queued, 0 in flight
    s.emit("start", job=1)
    s.emit("start", job=2)
    assert s.retry_after_hint(1) == 6.0  # (1 queued + 2 in flight) x 2s
    s.emit("done", job=1, wall_s=2.0, verdict=0)
    assert s.retry_after_hint(1) == 4.0  # one landed: (1 + 1) x avg


def test_stats_cache_loaded_is_additive_across_events():
    s = ServiceStats(None)
    s.emit("cache_loaded", entries=4)
    s.emit("cache_loaded", entries=3)
    # Regression: this used to be an assignment, so a second replay
    # (multi-segment boot) silently overwrote the first.
    assert s.snapshot()["cache_loaded"] == 7


# -- supervised-device degradation -------------------------------------------


def test_wedged_device_job_degrades_to_cpu(tmp_path, monkeypatch):
    """A job whose device escalation never answers (wedged TPU: supervise
    returns None) must still get a verdict — from the unbounded CPU close
    — and the degradation must be observable in the stats stream."""
    from s2_verification_tpu.checker.oracle import (
        CheckOutcome,
        CheckResult,
        check,
    )
    from s2_verification_tpu.service import scheduler as sched_mod

    real_cpu_check = sched_mod._cpu_check

    def budget_always_expires(hist, budget):
        if budget is None:  # the unbounded close: answer for real
            return real_cpu_check(hist, None)
        return CheckResult(outcome=CheckOutcome.UNKNOWN), "oracle"

    monkeypatch.setattr(sched_mod, "_cpu_check", budget_always_expires)
    monkeypatch.setattr(
        sched_mod.Scheduler,
        "_escalate_device",
        lambda self, job: (None, "device-supervised"),
    )

    cfg = _daemon_cfg(
        tmp_path, device="supervised", time_budget_s=1.0, unbounded_close=True
    )
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        reply = client.submit(good_history(), client="wedge", no_viz=True)
    assert reply["verdict"] == 0
    assert reply["backend"].endswith("-unbounded")  # the CPU close decided
    events = _events(tmp_path)
    degrades = [e for e in events if e["ev"] == "degrade"]
    assert len(degrades) == 1 and degrades[0]["to"] == "cpu"
    stops = [e for e in events if e["ev"] == "serve_stop"]
    assert stops and stops[0]["degraded"] == 1


def test_supervise_wedged_child_degrades_to_none(tmp_path):
    """Real supervision path: a child that never finishes an attempt
    (timeout kills it mid-import) exhausts its restart budget and returns
    None — the scheduler's degrade signal."""
    from s2_verification_tpu.service.supervise import supervised_device_check

    events = list(ev.iter_history(good_history()))
    res = supervised_device_check(
        events,
        spool_dir=str(tmp_path / "spool"),
        job_id=1,
        attempt_timeout_s=0.2,  # killed long before jax can even import
        max_restarts=0,
        probe=False,
    )
    assert res is None
