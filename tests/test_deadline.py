"""End-to-end deadlines: admission refusal, queue expiry, mid-search
cancellation (CPU and supervised-child paths), router decrement.

The deadline is a *remaining budget in seconds* riding the submit frame.
Every test here asserts the three observable promises of cooperative
cancellation: the client gets a definite ``DeadlineExceeded`` (never a
fake verdict), the worker/lease is freed within deadline + grace, and
``verifyd_jobs_cancelled_total{reason=...}`` counts the event.
"""

import io
import json
import threading
import time

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.oracle import CheckOutcome, CheckResult
from s2_verification_tpu.service import scheduler as sched_mod
from s2_verification_tpu.service.cache import history_fingerprint
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.overload import CancelToken
from s2_verification_tpu.service.router import (
    BackendSpec,
    RouterConfig,
    VerifydRouter,
)
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

# -- fixtures ----------------------------------------------------------------


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history(base: int = 100) -> str:
    h = H()
    h.append_ok(1, [base + 1], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([base + 1]))
    return _text(h)


def _fingerprint(text: str) -> str:
    return history_fingerprint(
        prepare(list(ev.iter_history(text)), elide_trivial=True)
    )


def _ring_key(text: str) -> str:
    """The router places by the prefix-affinity key, not the raw
    fingerprint (see VerifydRouter._affinity_key)."""
    hist = prepare(list(ev.iter_history(text)), elide_trivial=True)
    return VerifydRouter._affinity_key(hist, history_fingerprint(hist))


def _daemon_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        unbounded_close=False,
        out_dir=str(tmp_path / "viz"),
        stats_log=str(tmp_path / "stats.jsonl"),
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _events(tmp_path) -> list[dict]:
    with open(tmp_path / "stats.jsonl", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _cancelled(daemon, reason: str) -> float:
    return daemon.registry.get("verifyd_jobs_cancelled_total").value(
        reason=reason
    )


def _sleepy_cpu_check(monkeypatch):
    """A CPU stage that honestly consumes its budget and never decides —
    the shape of a history the oracle cannot close quickly."""

    def sleepy(hist, budget):
        time.sleep(min(budget if budget is not None else 0.5, 2.0))
        return CheckResult(outcome=CheckOutcome.UNKNOWN), "oracle"

    monkeypatch.setattr(sched_mod, "_cpu_check", sleepy)


# -- the token itself --------------------------------------------------------


def test_cancel_token_deadline_and_first_reason_wins():
    tok = CancelToken(time.monotonic() + 60.0)
    assert tok.check() is None
    assert 59.0 < tok.remaining() <= 60.0
    assert tok.cancel("client_gone") is True
    assert tok.cancel("shutdown") is False  # first reason sticks
    assert tok.check() == "client_gone"

    expired = CancelToken(time.monotonic() - 0.01)
    assert expired.check() == "deadline"  # auto-cancels on the clock
    assert expired.remaining() == 0.0

    unbounded = CancelToken()
    assert unbounded.check() is None and unbounded.remaining() is None


# -- admission ---------------------------------------------------------------


def test_deadline_already_expired_at_admission(tmp_path):
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path, timeout=30)
        with pytest.raises(VerifydError) as ei:
            client.submit(good_history(), no_viz=True, deadline_s=0.0)
        assert ei.value.cls == "DeadlineExceeded"
        assert ei.value.extra.get("reason") == "deadline"
        shed = daemon.registry.get("verifyd_admission_shed_total")
        assert shed.value(reason="deadline") == 1
        # Shed before the journal/queue: nothing was admitted.
        assert daemon.stats.snapshot()["completed"] == 0
    events = _events(tmp_path)
    assert [e for e in events if e["ev"] == "admission_shed"]


# -- queue expiry (cancellation boundary #1) ---------------------------------


def test_deadline_expires_in_queue_never_starts(tmp_path, monkeypatch):
    _sleepy_cpu_check(monkeypatch)
    cfg = _daemon_cfg(tmp_path, time_budget_s=0.8)
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path, timeout=30)
        blocker_reply = {}

        def blocker():
            blocker_reply.update(
                client.submit(good_history(100), client="slow", no_viz=True)
            )

        t = threading.Thread(target=blocker)
        t.start()
        time.sleep(0.2)  # the worker is now inside the sleepy search
        with pytest.raises(VerifydError) as ei:
            VerifydClient(cfg.socket_path, timeout=30).submit(
                good_history(200), client="doomed", no_viz=True,
                deadline_s=0.2,
            )
        t.join(timeout=10)
        assert ei.value.cls == "DeadlineExceeded"
        assert blocker_reply["outcome"] == "unknown"  # bystander unharmed
        assert _cancelled(daemon, "deadline") == 1
    events = _events(tmp_path)
    cancels = [e for e in events if e["ev"] == "job_cancelled"]
    assert len(cancels) == 1
    c = cancels[0]
    assert c["reason"] == "deadline" and c["started"] is False
    assert c["queue_wait_s"] >= 0.2  # it sat out its whole budget queued
    # Never started: no start event for the doomed client.
    assert not [
        e for e in events if e["ev"] == "start" and e["client"] == "doomed"
    ]


# -- mid-search expiry on the CPU path (boundary #2) -------------------------


def test_deadline_expires_mid_cpu_search(tmp_path, monkeypatch):
    _sleepy_cpu_check(monkeypatch)
    cfg = _daemon_cfg(tmp_path, time_budget_s=30.0, deadline_grace_s=1.0)
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path, timeout=30)
        t0 = time.monotonic()
        with pytest.raises(VerifydError) as ei:
            client.submit(good_history(), no_viz=True, deadline_s=0.4)
        elapsed = time.monotonic() - t0
        assert ei.value.cls == "DeadlineExceeded"
        # The 30s CPU budget was clamped to the 0.4s remaining: the
        # worker freed within deadline + grace (+ scheduling slack).
        assert elapsed < 0.4 + 1.0 + 2.0
        assert _cancelled(daemon, "deadline") == 1
    cancels = [e for e in _events(tmp_path) if e["ev"] == "job_cancelled"]
    assert len(cancels) == 1 and cancels[0]["started"] is True


# -- mid-search expiry on the supervised-child path --------------------------


@pytest.mark.slow
def test_deadline_frees_supervised_child_and_lease(tmp_path, monkeypatch):
    """The hard case: the job is inside a supervised escalation child (a
    real subprocess) when the deadline passes.  The drive loop's cancel
    poll must SIGTERM the child, release the device lease, and answer
    DeadlineExceeded — all within deadline + grace.

    The child is made genuinely intractable by a ``sitecustomize.py``
    injected via PYTHONPATH that sleeps at interpreter startup — the
    real-subprocess analogue of a search that cannot finish in time."""

    def instant_unknown(hist, budget):
        return CheckResult(outcome=CheckOutcome.UNKNOWN), "oracle"

    monkeypatch.setattr(sched_mod, "_cpu_check", instant_unknown)

    wedge = tmp_path / "wedge"
    wedge.mkdir()
    (wedge / "sitecustomize.py").write_text(
        "import os, time\n"
        "if os.environ.get('VERIFYD_TEST_WEDGE_CHILD') == '1':\n"
        "    time.sleep(120)\n",
        encoding="utf-8",
    )
    import os as _os

    existing = _os.environ.get("PYTHONPATH", "")
    monkeypatch.setenv(
        "PYTHONPATH",
        str(wedge) + ((_os.pathsep + existing) if existing else ""),
    )
    monkeypatch.setenv("VERIFYD_TEST_WEDGE_CHILD", "1")

    text = good_history()
    cfg = _daemon_cfg(
        tmp_path,
        device="supervised",
        mesh_devices=1,
        spool_dir=str(tmp_path / "spool"),
        attempt_timeout_s=60.0,
        time_budget_s=0.05,
        deadline_grace_s=1.0,
        state_dir=str(tmp_path / "state"),
    )
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path, timeout=60)
        t0 = time.monotonic()
        with pytest.raises(VerifydError) as ei:
            client.submit(text, no_viz=True, deadline_s=1.0)
        elapsed = time.monotonic() - t0
        assert ei.value.cls == "DeadlineExceeded"
        # deadline (1.0) + grace (1.0) + spawn/kill slack.
        assert elapsed < 1.0 + 1.0 + 4.0
        # The lease went back to the pool the moment the child died.
        assert daemon.device_pool.snapshot()["in_use"] == 0
        assert _cancelled(daemon, "deadline") == 1
        # Our own SIGTERM is not a crash: the poison ledger stays clean.
        assert daemon.quarantine.crash_count(_fingerprint(text)) == 0
    cancels = [e for e in _events(tmp_path) if e["ev"] == "job_cancelled"]
    assert len(cancels) == 1
    assert cancels[0]["reason"] == "deadline" and cancels[0]["started"] is True


# -- router decrement across failover ----------------------------------------


def _router_cfg(tmp_path, names) -> RouterConfig:
    return RouterConfig(
        listen=str(tmp_path / "router.sock"),
        backends=tuple(
            BackendSpec(n, str(tmp_path / f"{n}.sock")) for n in names
        ),
        probe_interval_s=30.0,
        breaker_failures=5,
        max_failovers=2,
    )


def test_router_decrements_deadline_across_failover(tmp_path):
    """A failed attempt burns real wall clock; the next backend must see
    a *smaller* remaining budget, not the client's original number."""
    from s2_verification_tpu.service.client import VerifydUnavailable

    router = VerifydRouter(_router_cfg(tmp_path, ("a", "b")))
    calls = []

    def dying(text, **kw):
        calls.append(("dead", kw.get("deadline_s")))
        time.sleep(0.25)  # the budget this attempt burned
        raise VerifydUnavailable("Unavailable", "connect refused")

    def answering(text, **kw):
        calls.append(("live", kw.get("deadline_s")))
        return {"verdict": 0, "outcome": "ok", "cached": False}

    # Whichever node the ring prefers dies first; the other answers.
    order = router._candidate_order(_ring_key(good_history()))[0]
    order[0].client.submit = dying
    order[1].client.submit = answering

    reply = router._route_submit(
        {"op": "submit", "history": good_history(), "deadline": 2.0}
    )
    assert reply["ok"]["verdict"] == 0 and reply["ok"]["node"] == order[1].name
    assert [kind for kind, _ in calls] == ["dead", "live"]
    first, second = calls[0][1], calls[1][1]
    assert first is not None and first <= 2.0
    # The second attempt's budget is short the ~0.25s the first burned.
    assert second <= first - 0.2


def test_router_refuses_third_node_when_deadline_spent(tmp_path):
    from s2_verification_tpu.service.client import VerifydUnavailable

    router = VerifydRouter(_router_cfg(tmp_path, ("a", "b")))

    def dying(text, **kw):
        time.sleep(0.3)
        raise VerifydUnavailable("Unavailable", "connect refused")

    untouched = []
    order = router._candidate_order(_ring_key(good_history()))[0]
    order[0].client.submit = dying
    order[1].client.submit = lambda *a, **kw: untouched.append(1)

    reply = router._route_submit(
        {"op": "submit", "history": good_history(), "deadline": 0.2}
    )
    e = reply["err"]
    assert e["class"] == "DeadlineExceeded" and e["reason"] == "deadline"
    assert e["attempts"] == 1
    assert untouched == []  # no stale-clock handoff to a third node
