"""Differential validation of the DFS oracle against a brute-force checker.

The brute-force checker enumerates every total order of ops consistent with
real time and replays the model — exponential, but independent of the DFS
machinery (no entry list, no memoization, no elision). Random small histories
generated from a toy replayable stream keep both sides honest.
"""

import itertools
import random

from helpers import H, fold
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.models.stream import INIT_STATE, step_set


def brute_force_ok(history) -> bool:
    ops = history.ops
    n = len(ops)
    if n == 0:
        return True

    def consistent(order):
        # later-called op may not precede an op that returned before its call
        pos = {j: k for k, j in enumerate(order)}
        for a in ops:
            for b in ops:
                if a.ret < b.call and pos[a.index] > pos[b.index]:
                    return False
        return True

    for order in itertools.permutations(range(n)):
        if not consistent(order):
            continue
        states = [INIT_STATE]
        for j in order:
            states = step_set(states, ops[j].inp, ops[j].out)
            if not states:
                break
        if states:
            return True
    return False


def random_history(rng: random.Random) -> H:
    """A small random concurrent history over a simulated stream.

    Ops are issued by 2-3 clients with random interleaving of call/finish;
    outputs are produced by a real sequential stream applied at finish time,
    with random lies injected so both OK and ILLEGAL cases appear.
    """
    h = H()
    n_clients = rng.randint(2, 3)
    stream: list[int] = []
    open_ops: list[tuple[int, int, str, list[int], int | None]] = []
    next_hash = 100
    for _ in range(rng.randint(3, 6)):
        if open_ops and (rng.random() < 0.5 or len(open_ops) == n_clients):
            # Finish a random open op; apply it to the stream now.
            i = rng.randrange(len(open_ops))
            client, op, kind, hashes, match = open_ops.pop(i)
            lie = rng.random() < 0.15
            if kind == "append":
                applies = match is None or match == len(stream)
                if rng.random() < 0.2:
                    from s2_verification_tpu.utils.events import (
                        AppendIndefiniteFailure,
                    )

                    if applies and rng.random() < 0.5:
                        stream.extend(hashes)
                    h.finish(client, op, AppendIndefiniteFailure())
                elif applies or lie:
                    from s2_verification_tpu.utils.events import AppendSuccess

                    if applies:
                        stream.extend(hashes)
                    tail = len(stream) + (1 if lie and rng.random() < 0.5 else 0)
                    h.finish(client, op, AppendSuccess(tail=tail))
                else:
                    from s2_verification_tpu.utils.events import (
                        AppendDefiniteFailure,
                    )

                    h.finish(client, op, AppendDefiniteFailure())
            elif kind == "read":
                from s2_verification_tpu.utils.events import ReadSuccess

                sh = fold(stream)
                if lie:
                    sh ^= 0xBAD
                h.finish(client, op, ReadSuccess(tail=len(stream), stream_hash=sh))
            else:
                from s2_verification_tpu.utils.events import CheckTailSuccess

                tail = len(stream) + (1 if lie else 0)
                h.finish(client, op, CheckTailSuccess(tail=tail))
        else:
            # Start a new op on an idle client.
            busy = {c for c, *_ in open_ops}
            free = [c for c in range(1, n_clients + 1) if c not in busy]
            if not free:
                continue
            client = rng.choice(free)
            kind = rng.choice(["append", "append", "read", "check_tail"])
            if kind == "append":
                hashes = [next_hash + k for k in range(rng.randint(1, 3))]
                next_hash += 10
                match = len(stream) if rng.random() < 0.4 else None
                op = h.call_append(client, hashes, match=match)
                open_ops.append((client, op, kind, hashes, match))
            elif kind == "read":
                op = h.call_read(client)
                open_ops.append((client, op, kind, [], None))
            else:
                op = h.call_check_tail(client)
                open_ops.append((client, op, kind, [], None))
    # Any still-open ops stay pending (open-op path).
    return h


def test_dfs_matches_brute_force_on_random_histories():
    rng = random.Random(0xC0FFEE)
    n_ok = n_bad = 0
    for trial in range(300):
        h = random_history(rng)
        hist_full = prepare(h.events, elide_trivial=False)
        if hist_full.num_ops > 7:
            continue
        expect = brute_force_ok(hist_full)
        got_plain = check(hist_full).outcome
        got_elided = check(prepare(h.events, elide_trivial=True)).outcome
        want = CheckOutcome.OK if expect else CheckOutcome.ILLEGAL
        assert got_plain == want, f"trial {trial}: DFS={got_plain} brute={want}"
        assert got_elided == want, f"trial {trial}: elided DFS diverged"
        n_ok += expect
        n_bad += not expect
    # The generator must actually produce both classes.
    assert n_ok > 20 and n_bad > 20, (n_ok, n_bad)
