"""End-to-end: fake S2 + workload clients produce linearizable histories.

The decisive property: whatever faults are injected, the *true* behavior of
the fake service is sequential, so every collected history must check OK.
This is the same invariant Antithesis asserts over the reference harness.
"""

import random

import pytest

from s2_verification_tpu.checker.oracle import CheckOutcome, check_events
from s2_verification_tpu.collector.collect import (
    CollectConfig,
    collect_history,
    collect_to_file,
)
from s2_verification_tpu.collector.fake_s2 import FakeS2Stream, FaultPlan, _Record
from s2_verification_tpu.collector.workloads import generate_records
from s2_verification_tpu.utils import events as ev


def cfg(**kw):
    base = dict(
        num_concurrent_clients=3,
        num_ops_per_client=15,
        seed=7,
        indefinite_failure_backoff_s=0.0,
        faults=FaultPlan.chaos(intensity=0.25, max_latency=0.001),
    )
    base.update(kw)
    return CollectConfig(**base)


@pytest.mark.parametrize("workflow", ["regular", "match-seq-num", "fencing"])
def test_collected_history_is_linearizable(workflow):
    events = collect_history(cfg(workflow=workflow))
    assert len(events) > 20
    res = check_events(events)
    assert res.outcome == CheckOutcome.OK


@pytest.mark.parametrize("seed", range(5))
def test_many_seeds_linearizable(seed):
    events = collect_history(cfg(seed=seed, workflow="match-seq-num"))
    assert check_events(events).outcome == CheckOutcome.OK


def test_deferred_indefinite_finishes_flushed_last():
    events = collect_history(cfg(seed=3, workflow="match-seq-num"))
    # Once the first deferred AppendIndefiniteFailure appears, everything
    # after it must be one too (collect-history.rs:185-193).
    kinds = [type(e.event).__name__ for e in events]
    if "AppendIndefiniteFailure" in kinds:
        first = kinds.index("AppendIndefiniteFailure")
        assert all(k == "AppendIndefiniteFailure" for k in kinds[first:])


def test_fault_classes_all_appear():
    events = collect_history(
        cfg(seed=11, num_ops_per_client=40, workflow="match-seq-num")
    )
    kinds = {type(e.event).__name__ for e in events}
    assert "AppendSuccess" in kinds
    assert "AppendDefiniteFailure" in kinds
    assert "AppendIndefiniteFailure" in kinds


def test_non_empty_stream_gets_rectifying_append():
    stream = FakeS2Stream(rng=random.Random(1))
    stream.records.extend([_Record(b"pre1"), _Record(b"pre2")])
    events = collect_history(cfg(seed=2, faults=FaultPlan()), stream=stream)
    first = events[0]
    assert isinstance(first.event, ev.AppendStart)
    assert first.client_id == 0
    assert first.event.num_records == 2
    assert isinstance(events[1].event, ev.AppendSuccess)
    assert events[1].event.tail == 2
    assert check_events(events).outcome == CheckOutcome.OK


def test_generate_records_respects_batch_budget():
    rng = random.Random(5)
    for _ in range(50):
        bodies, hashes = generate_records(rng, rng.randint(1, 999))
        assert len(bodies) == len(hashes) >= 1
        metered = sum(8 + len(b) for b in bodies)
        assert metered <= 1024 + 8 + 1024  # last record may exceed by its size
        # Faithful bound: bytes before the last record fit under the cap.
        assert sum(8 + len(b) for b in bodies[:-1]) < 1024


def test_client_rotation_capped():
    # Indefinite failures on every append: clients rotate ids until the id
    # budget runs out, then stop early.  (Like the reference, only *rotation*
    # checks the cap — the initial id take is uncapped, history.rs:190,161-167.)
    events = collect_history(
        cfg(
            seed=9,
            num_concurrent_clients=4,
            num_ops_per_client=50,
            faults=FaultPlan(p_append_indefinite=1.0),
            max_client_ids=6,
        )
    )
    client_ids = {e.client_id for e in events}
    # Ids come from one shared counter: 4 initial takes + at most one
    # successful rotation per id below the cap.
    assert len(client_ids) <= 4 + 6
    # Every append failed indefinitely, so every client stopped early.
    n_ops = len({e.op_id for e in events})
    assert n_ops < 4 * 50
    # Each id's ops are sequential and every indefinite finish is deferred.
    assert check_events(events).outcome == CheckOutcome.OK


def test_collect_to_file_roundtrip(tmp_path):
    path = collect_to_file(cfg(seed=4), out_dir=str(tmp_path))
    events = ev.read_history(path)
    assert len(events) > 10
    assert check_events(events).outcome == CheckOutcome.OK


def test_byte_deterministic_replay():
    # Virtual time: the same seed must reproduce the history byte-for-byte,
    # regardless of wall-clock scheduling (reference parity: turmoil /
    # Antithesis DST, SURVEY.md §2.2).
    import io

    c = cfg(
        num_concurrent_clients=4,
        num_ops_per_client=30,
        workflow="match-seq-num",
        indefinite_failure_backoff_s=0.5,
    )
    outs = []
    for _ in range(3):
        buf = io.StringIO()
        ev.write_history(collect_history(c), buf)
        outs.append(buf.getvalue())
    assert outs[0] == outs[1] == outs[2]
    assert outs[0].strip(), "history must be non-empty"


def test_distinct_seeds_differ():
    import io

    a, b = io.StringIO(), io.StringIO()
    ev.write_history(collect_history(cfg(seed=1)), a)
    ev.write_history(collect_history(cfg(seed=2)), b)
    assert a.getvalue() != b.getvalue()


def test_debug_narration(caplog):
    # S2VTPU_LOG=DEBUG narrates the run like RUST_LOG=trace does for the
    # reference (history.rs:408-439): per-op outcomes, injected faults,
    # rotations, and the deferred-finish flush.
    import logging

    with caplog.at_level(logging.DEBUG, logger="s2_verification_tpu"):
        collect_history(
            CollectConfig(
                num_concurrent_clients=3,
                num_ops_per_client=20,
                workflow="match-seq-num",
                seed=11,
                faults=FaultPlan.chaos(0.3),
            )
        )
    text = caplog.text
    assert "append" in text and "-> Append" in text
    assert "inject:" in text
    assert "flushing" in text


def test_stream_reuse_across_collections_does_not_deadlock():
    # Regression: a stream reused across runs (rectifying-append scenario)
    # kept the first run's virtual clock; the second run's clients then
    # parked on a scheduler that could never advance — a deadlock.
    import random

    stream = FakeS2Stream(rng=random.Random(3), faults=FaultPlan.chaos(0.3))
    cfg = CollectConfig(
        num_concurrent_clients=2, num_ops_per_client=10, seed=9,
        faults=FaultPlan.chaos(0.3),
    )
    first = collect_history(cfg, stream)
    assert stream.clock is None  # restored after the run
    second = collect_history(CollectConfig(
        num_concurrent_clients=2, num_ops_per_client=10, seed=10,
        faults=FaultPlan.chaos(0.3),
    ), stream)
    assert first and second
    # The second history starts from the non-empty stream: rectified.
    from s2_verification_tpu.checker.entries import prepare
    from s2_verification_tpu.checker.oracle import check
    assert check(prepare(second)).ok


def test_violating_stream_yields_illegal_history():
    # The dual of every test above: when the service itself cheats (here: a
    # campaign stream that acks an append without applying it), the same
    # client path must produce a history the checker REJECTS — the collector
    # is a witness, not a launderer.
    from s2_verification_tpu.collector.campaign import (
        collect_labeled,
        get_campaign,
    )

    events, label = collect_labeled(get_campaign("drop-acked"), seed=11)
    assert label["expect"] == "illegal"
    assert check_events(events).outcome == CheckOutcome.ILLEGAL


def test_transport_seam_structural():
    # VERDICT r2 #8: the workloads are typed against the transport seam;
    # the fake satisfies it structurally (no inheritance), so a
    # network-backed implementation is a driver swap, not surgery.
    from s2_verification_tpu.collector.transport import S2StreamTransport

    assert isinstance(FakeS2Stream(), S2StreamTransport)


def test_alternative_transport_drives_collection():
    # A different class implementing the protocol (here a delegating
    # wrapper standing in for a real-endpoint client) runs the full
    # collection pipeline unchanged and still yields a linearizable
    # history.
    inner = FakeS2Stream(
        rng=random.Random(0xB0B),
        faults=FaultPlan.chaos(intensity=0.25, max_latency=0.001),
    )

    class WrapperTransport:
        clock = None

        async def append(self, bodies, **kw):
            inner.clock = self.clock
            return await inner.append(bodies, **kw)

        async def read_all(self):
            inner.clock = self.clock
            return await inner.read_all()

        async def check_tail(self):
            inner.clock = self.clock
            return await inner.check_tail()

        def snapshot_bodies(self):
            return inner.snapshot_bodies()

    from s2_verification_tpu.collector.transport import S2StreamTransport

    wrapper = WrapperTransport()
    assert isinstance(wrapper, S2StreamTransport)
    events = collect_history(cfg(workflow="match-seq-num"), stream=wrapper)
    assert events
    assert check_events(events).outcome == CheckOutcome.OK
