"""Shared test helpers: a compact history builder over the wire vocabulary."""

from s2_verification_tpu.utils.events import (
    AppendDefiniteFailure,
    AppendIndefiniteFailure,
    AppendStart,
    AppendSuccess,
    CheckTailFailure,
    CheckTailStart,
    CheckTailSuccess,
    LabeledEvent,
    ReadFailure,
    ReadStart,
    ReadSuccess,
)
from s2_verification_tpu.utils.hashing import fold_record_hashes


class H:
    """History builder: explicit call/finish emission for concurrency tests."""

    def __init__(self):
        self.events: list[LabeledEvent] = []
        self._next_op = 0

    def _start(self, client, payload):
        op = self._next_op
        self._next_op += 1
        self.events.append(LabeledEvent(payload, client, op))
        return op

    def call_append(self, client, hashes, set_token=None, token=None, match=None):
        return self._start(
            client,
            AppendStart(
                num_records=len(hashes),
                record_hashes=tuple(hashes),
                set_fencing_token=set_token,
                fencing_token=token,
                match_seq_num=match,
            ),
        )

    def call_read(self, client):
        return self._start(client, ReadStart())

    def call_check_tail(self, client):
        return self._start(client, CheckTailStart())

    def finish(self, client, op, payload):
        self.events.append(LabeledEvent(payload, client, op))

    # -- sequential conveniences (call + immediate finish) ------------------

    def append_ok(self, client, hashes, tail, **kw):
        op = self.call_append(client, hashes, **kw)
        self.finish(client, op, AppendSuccess(tail=tail))
        return op

    def append_definite_fail(self, client, hashes, **kw):
        op = self.call_append(client, hashes, **kw)
        self.finish(client, op, AppendDefiniteFailure())
        return op

    def append_indefinite_fail(self, client, hashes, **kw):
        op = self.call_append(client, hashes, **kw)
        self.finish(client, op, AppendIndefiniteFailure())
        return op

    def read_ok(self, client, tail, stream_hash):
        op = self.call_read(client)
        self.finish(client, op, ReadSuccess(tail=tail, stream_hash=stream_hash))
        return op

    def read_fail(self, client):
        op = self.call_read(client)
        self.finish(client, op, ReadFailure())
        return op

    def check_tail_ok(self, client, tail):
        op = self.call_check_tail(client)
        self.finish(client, op, CheckTailSuccess(tail=tail))
        return op

    def check_tail_fail(self, client):
        op = self.call_check_tail(client)
        self.finish(client, op, CheckTailFailure())
        return op


def fold(hashes, start=0):
    return fold_record_hashes(start, hashes)


def assert_valid_linearization(hist, order):
    """Independent witness validation: the order must cover every op exactly
    once, extend the real-time partial order (A.ret < B.call => A before B),
    and drive a non-empty candidate-state set through every step."""
    from s2_verification_tpu.models.stream import INIT_STATE, step_set

    ops = hist.ops
    assert sorted(order) == list(range(len(ops)))
    pos = {j: i for i, j in enumerate(order)}
    for a in ops:
        for b in ops:
            if a.ret < b.call:
                assert pos[a.index] < pos[b.index], (a.index, b.index)
    states = [INIT_STATE]
    for j in order:
        states = step_set(states, ops[j].inp, ops[j].out)
        assert states, f"empty state set linearizing op {j}"
