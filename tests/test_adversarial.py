"""Differential tests for the adversarial history family.

``collector/adversarial.py`` generates the search-hardness regime the
north star names (histories whose ambiguity is global: k overlapping
ambiguous appends + one pinning read — reference README.md:74 "the more
clients, the harder").  These tests pin the generator against every
engine at small k, including the ILLEGAL-by-exhaustion path.
"""

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.frontier import check_frontier
from s2_verification_tpu.checker.device import check_device
from s2_verification_tpu.checker.native import check_native, native_available
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.collector.adversarial import (
    adversarial_events,
    ordered_subsets_count,
)


def test_ordered_subsets_count():
    # sum_{m=0..k} k!/(k-m)!
    assert ordered_subsets_count(0) == 1
    assert ordered_subsets_count(1) == 2
    assert ordered_subsets_count(2) == 5  # {}, a, b, ab, ba
    assert ordered_subsets_count(3) == 16
    assert ordered_subsets_count(8) == 109601


@pytest.mark.parametrize("k,applied", [(2, 1), (3, 0), (3, 2), (4, 2), (4, 4)])
def test_satisfiable_is_ok_on_all_engines(k, applied):
    hist = prepare(adversarial_events(k, batch=3, applied=applied, seed=k))
    want = check(hist)
    assert want.outcome == CheckOutcome.OK
    assert check_frontier(hist).outcome == CheckOutcome.OK
    dev = check_device(hist, beam=False, start_frontier=16, max_frontier=1024)
    assert dev.outcome == CheckOutcome.OK
    if native_available():
        assert check_native(hist).outcome == CheckOutcome.OK


@pytest.mark.parametrize("k", [2, 3, 4])
def test_unsatisfiable_is_illegal_by_exhaustion(k):
    # The corrupted pin admits no ordered subset; every engine must exhaust
    # the full space (no shortcut exists) and conclude ILLEGAL.
    hist = prepare(adversarial_events(k, batch=3, seed=7, unsatisfiable=True))
    want = check(hist)
    assert want.outcome == CheckOutcome.ILLEGAL
    # Exhaustion really visited the space: at least one state per ordered
    # subset of the k appends was stepped.
    assert want.steps >= ordered_subsets_count(k)
    assert check_frontier(hist).outcome == CheckOutcome.ILLEGAL
    dev = check_device(hist, beam=False, start_frontier=16, max_frontier=1024)
    assert dev.outcome == CheckOutcome.ILLEGAL
    if native_available():
        assert check_native(hist).outcome == CheckOutcome.ILLEGAL


def test_adversarial_beam_ok_is_conclusive():
    # Beam mode may prune, but an OK it does report is sound.
    hist = prepare(adversarial_events(5, batch=4, seed=1))
    res = check_device(hist, beam=True, start_frontier=16, max_frontier=512)
    assert res.outcome in (CheckOutcome.OK, CheckOutcome.UNKNOWN)
    if res.outcome == CheckOutcome.OK:
        assert check(hist).outcome == CheckOutcome.OK


def test_applied_bounds_validated():
    with pytest.raises(ValueError):
        adversarial_events(3, applied=4)
    with pytest.raises(ValueError):
        adversarial_events(3, applied=-1)


def test_seed_reproducibility():
    a = adversarial_events(4, batch=5, seed=9)
    b = adversarial_events(4, batch=5, seed=9)
    assert a == b
    c = adversarial_events(4, batch=5, seed=10)
    assert a != c
