"""PerfSentinel: EWMA fold math, consecutive filter, re-arm, wiring.

The fold is unit-tested directly (observe), then through the event
stream (observe_event → ServiceStats re-emit → counters → AlertEngine
routing) — the full path a live regression takes from a slow job to a
delivered page.
"""

from s2_verification_tpu.obs.metrics import MetricsRegistry
from s2_verification_tpu.obs.sentinel import (
    PerfSentinel,
    SentinelConfig,
    ewma_drift,
)

FAST = SentinelConfig(min_samples=3, consecutive=2, floor_s=0.001)


def _warm(s, shape="4x2x8", n=5, wall=0.1):
    for _ in range(n):
        assert s.observe(shape, wall) is None


def test_ewma_drift_predicate():
    assert ewma_drift(1.76, 1.0, 0.75)
    assert not ewma_drift(1.75, 1.0, 0.75)
    assert not ewma_drift(0.5, 1.0, 0.75)


def test_cold_start_never_fires():
    s = PerfSentinel(SentinelConfig(min_samples=10, consecutive=1))
    for _ in range(10):
        assert s.observe("shape", 5.0) is None
    # 11th sample is judged, but sits on its own baseline: still quiet
    assert s.observe("shape", 5.0) is None


def test_consecutive_filter_and_report_fields():
    s = PerfSentinel(FAST)
    _warm(s, n=5)
    assert s.observe("4x2x8", 1.0) is None  # streak 1 of 2
    report = s.observe("4x2x8", 1.0)  # streak 2: fires
    assert report is not None
    assert report["shape"] == "4x2x8"
    assert report["wall_s"] == 1.0
    # baseline folded once at alpha/8 by the first slow sample:
    # 0.1 + (0.25/8)*(1.0-0.1) ≈ 0.128
    assert 0.09 < report["baseline_wall_s"] < 0.15
    assert report["ratio"] > 6
    assert report["streak"] == 2
    assert report["samples"] == 7


def test_single_spike_is_not_a_regression():
    s = PerfSentinel(FAST)
    _warm(s, n=5)
    assert s.observe("4x2x8", 1.0) is None  # one blip
    assert s.observe("4x2x8", 0.1) is None  # back in band: streak reset
    assert s.observe("4x2x8", 1.0) is None  # streak restarts at 1
    assert s.observe("4x2x8", 1.0) is not None


def test_latched_until_recovery_then_rearms():
    s = PerfSentinel(FAST)
    _warm(s, n=5)
    s.observe("4x2x8", 1.0)
    assert s.observe("4x2x8", 1.0) is not None  # fires
    assert s.observe("4x2x8", 1.0) is None  # latched: no page storm
    assert s.observe("4x2x8", 0.1) is None  # recovery re-arms
    s.observe("4x2x8", 1.0)
    assert s.observe("4x2x8", 1.0) is not None  # second regression pages
    assert s.snapshot()["shapes"]["4x2x8"]["regressions"] == 2


def test_spike_barely_moves_baseline():
    s = PerfSentinel(FAST)
    _warm(s, n=5)
    before = s.snapshot()["shapes"]["4x2x8"]["baseline_wall_s"]
    s.observe("4x2x8", 10.0)  # out of band: folds at alpha/8
    after = s.snapshot()["shapes"]["4x2x8"]["baseline_wall_s"]
    assert after < before + (10.0 - before) * FAST.alpha / 4
    # an in-band sample folds at full alpha by comparison
    s2 = PerfSentinel(FAST)
    _warm(s2, n=5)
    s2.observe("4x2x8", 0.15)
    moved = s2.snapshot()["shapes"]["4x2x8"]["baseline_wall_s"]
    assert moved > before + (0.15 - before) * FAST.alpha * 0.9


def test_floor_guards_noise_shapes():
    s = PerfSentinel(SentinelConfig(min_samples=2, consecutive=1, floor_s=0.005))
    for _ in range(5):
        s.observe("tiny", 0.0001)
    # 30x drift but still under the floor: never judged
    assert s.observe("tiny", 0.003) is None


def test_shapes_are_independent():
    s = PerfSentinel(FAST)
    _warm(s, shape="a", n=5, wall=0.1)
    _warm(s, shape="b", n=5, wall=2.0)
    s.observe("a", 1.0)
    assert s.observe("a", 1.0) is not None  # 10x on shape a
    assert s.observe("b", 2.0) is None  # shape b undisturbed


def test_metrics_and_snapshot():
    reg = MetricsRegistry()
    s = PerfSentinel(FAST, registry=reg)
    _warm(s, n=5)
    s.observe("4x2x8", 1.0)
    s.observe("4x2x8", 1.0)
    assert reg.get("verifyd_perf_regressions_total").value(shape="4x2x8") == 1
    assert reg.get("verifyd_perf_baseline_wall_seconds").value(shape="4x2x8") > 0
    snap = s.snapshot()
    assert snap["regressions"] == 1
    assert snap["config"]["band"] == FAST.band
    st = snap["shapes"]["4x2x8"]
    assert st["fired"] and st["streak"] == 2 and st["samples"] == 7


def test_event_stream_routes_to_alert_engine():
    """done events → sentinel → perf_regression re-emit → counter + alert."""
    from s2_verification_tpu.obs.alerts import AlertEngine
    from s2_verification_tpu.service.stats import ServiceStats

    reg = MetricsRegistry()
    fired = []

    class _CaptureEngine(AlertEngine):
        def _deliver(self, alert):
            fired.append(alert["rule"].name)

    eng = _CaptureEngine("http://127.0.0.1:1/unused", registry=reg)
    sentinel = PerfSentinel(FAST, registry=reg)
    stats = ServiceStats(
        sink=None, registry=reg, sentinel=sentinel, alerts=eng
    )
    try:
        for _ in range(5):
            stats.emit("done", shape="4x2x8", backend="native", wall_s=0.1)
        stats.emit("done", shape="4x2x8", backend="native", wall_s=1.0)
        stats.emit("done", shape="4x2x8", backend="native", wall_s=1.0)
        assert eng.flush(timeout=10.0)
        assert fired == ["perf_regression"]
        snap = stats.snapshot()
        assert snap["perf_regressions"] == 1
        assert snap["sentinel"]["regressions"] == 1
    finally:
        eng.close()
