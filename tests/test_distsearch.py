"""Distributed frontier search: partition canon, epoch fencing, ledger
recovery, and the end-to-end coordinated route.

Unit layers are clockless and wire-free (pack/unpack byte canon, digest
partitioning, segment planning, the coordinator's merge fence, ledger
torn-tail recovery); the end-to-end layer boots three in-process
``Verifyd`` backends behind an in-process ``VerifydRouter`` and proves
verdict parity against the in-process CPU oracle — the SIGKILL story
lives in ``make distsearch`` (scripts/distsearch_check.py).
"""

import io
import json
import os
import struct

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.frontier import (
    check_frontier,
    check_frontier_auto,
)
from s2_verification_tpu.checker.oracle import CheckOutcome
from s2_verification_tpu.models.stream import INIT_STATE, StreamState
from s2_verification_tpu.service.client import VerifydClient
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.distsearch import (
    Coordinator,
    pack_states,
    part_ranges,
    partition_states,
    plan_segments,
    unpack_states,
)
from s2_verification_tpu.service.journal import (
    GRANTS_SUBDIR,
    GrantLedger,
    read_grants_cold,
)
from s2_verification_tpu.service.router import (
    BackendSpec,
    RouterConfig,
    VerifydRouter,
)
from s2_verification_tpu.utils import events as ev
from s2_verification_tpu.utils.events import AppendIndefiniteFailure

from helpers import H, fold


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def _branchy(rounds: int = 3, k: int = 2, base: int = 700) -> H:
    """``rounds`` rounds of ``k`` concurrent indefinite appends, each
    closed by a check-tail barrier pinning exactly one more applied
    record — every round doubles the candidate-state union, and every
    barrier is an event-closed cut for the segment planner."""
    h = H()
    for r in range(rounds):
        ops = [
            (10 + i, h.call_append(10 + i, [base + 10 * r + i]))
            for i in range(k)
        ]
        for c, op in ops:
            h.finish(c, op, AppendIndefiniteFailure())
        h.check_tail_ok(99, tail=r + 1)
    return h


# -- wire canon ---------------------------------------------------------------


def test_pack_unpack_roundtrip_byte_for_byte():
    states = (
        StreamState(tail=3, stream_hash=777, fencing_token=None),
        StreamState(tail=1, stream_hash=42, fencing_token=7),
        StreamState(tail=2, stream_hash=99, fencing_token=None),
    )
    payload = pack_states(states)
    # JSON round trip (the wire) then re-pack: identical bytes.
    wire = json.dumps(payload, separators=(",", ":"))
    back = unpack_states(json.loads(wire))
    assert set(back) == set(states)
    assert json.dumps(pack_states(back), separators=(",", ":")) == wire
    # Input order never matters: the canon sorts.
    assert pack_states(reversed(states)) == payload


def test_unpack_malformed_raises():
    for bad in ([[1, 2]], [["x", "y", None]], [1], [[1, 2, 3, 4]]):
        with pytest.raises(ValueError):
            unpack_states(bad)


def test_part_ranges_cover_disjoint():
    for n in (1, 2, 3, 7, 16):
        ranges = part_ranges(n)
        assert len(ranges) == n
        assert ranges[0][0] == 0 and ranges[-1][1] == 1 << 32
        for (_, hi), (lo, _) in zip(ranges, ranges[1:]):
            assert hi == lo  # contiguous, no gap, no overlap


def test_partition_states_disjoint_cover():
    states = [
        StreamState(tail=t, stream_hash=h, fencing_token=None)
        for t in range(6)
        for h in (t * 1000003, t * 17 + 5)
    ]
    for n in (1, 2, 3, 5):
        parts = partition_states(states, n)
        assert len(parts) <= n
        assert all(parts.values())  # empty ranges are dropped
        union = [s for ss in parts.values() for s in ss]
        assert sorted(union) == sorted(set(states))  # covering
        seen = set()
        for ss in parts.values():  # pairwise disjoint
            assert not (set(ss) & seen)
            seen.update(ss)


# -- segment planning ---------------------------------------------------------


def test_plan_segments_cuts_are_event_closed_and_partition_ops():
    h = _branchy(rounds=4, k=2)
    events = h.events
    hist = prepare(events, elide_trivial=True)
    segments = plan_segments(events, hist, 3)
    assert segments is not None and 2 <= len(segments) <= 3
    # Contiguous slices covering every event, op counts increasing to
    # the full op count.
    assert segments[0].event_lo == 0
    assert segments[-1].event_hi == len(events)
    for a, b in zip(segments, segments[1:]):
        assert a.event_hi == b.event_lo
        assert a.ops_hi < b.ops_hi
    assert segments[-1].ops_hi == len(hist.ops)
    for seg in segments[:-1]:
        # Event-closed: every op started in the prefix finished in it.
        open_ops = set()
        for le in events[: seg.event_hi]:
            key = (le.client_id, le.op_id)
            open_ops.add(key) if le.is_start else open_ops.discard(key)
        assert not open_ops, f"cut at {seg.event_hi} slices an op"
        # Op-consistent: ops_hi counts exactly the ops called before it.
        assert seg.ops_hi == sum(
            1 for op in hist.ops if op.call < seg.event_hi
        )
        # Boundary names come from the chain-hash prefix canon.
        assert not seg.key.startswith("seg:")


def test_plan_segments_degenerate_histories():
    assert plan_segments([], prepare([]), 3) is None
    h = H()
    h.append_ok(1, [5], tail=1)
    hist = prepare(h.events, elide_trivial=True)
    segs = plan_segments(h.events, hist, 3)  # no interior cut helps
    assert segs is not None and len(segs) == 1
    assert segs[0].event_hi == len(h.events)


def test_complete_cuts_holds_early_accept_until_union_is_exact():
    """A history whose tail is all indefinite appends early-accepts —
    fine for a verdict, fatal for a partition whose end union seeds the
    next segment.  ``complete_cuts=True`` defers the accept until the
    requested cut's union is the exact reachable set."""
    h = H()
    a = h.call_append(1, [11])
    b = h.call_append(2, [12])
    h.finish(1, a, AppendIndefiniteFailure())
    h.finish(2, b, AppendIndefiniteFailure())
    hist = prepare(h.events, elide_trivial=True)
    n = len(hist.ops)
    relaxed = check_frontier(hist, witness=False, snapshot_cuts=[n])
    assert relaxed.outcome == CheckOutcome.OK
    assert n not in (getattr(relaxed, "snapshots", None) or {})
    held = check_frontier(
        hist, witness=False, snapshot_cuts=[n], complete_cuts=True
    )
    assert held.outcome == CheckOutcome.OK
    union = set(getattr(held, "snapshots", {})[n])
    # Exact: every apply/skip interleaving of the two appends.
    assert union == {
        INIT_STATE,
        StreamState(tail=1, stream_hash=fold([11]), fencing_token=None),
        StreamState(tail=1, stream_hash=fold([12]), fencing_token=None),
        StreamState(tail=2, stream_hash=fold([11, 12]), fencing_token=None),
        StreamState(tail=2, stream_hash=fold([12, 11]), fencing_token=None),
    }


# -- the coordinator's merge fence --------------------------------------------


def test_coordinator_fences_stale_and_duplicate_deltas(tmp_path):
    led = GrantLedger(str(tmp_path / "state" / GRANTS_SUBDIR))
    coord = Coordinator(search="s-unit", nodes=lambda: [], ledger=led)
    try:
        seg, part = "chain:deadbeef", "00000000-80000000"
        coord._epochs[(seg, part)] = 5
        body = {"verdict": 0, "states": []}
        # A zombie's stale epoch is refused, counted, journaled.
        assert coord._accept_delta(seg, part, 4, body) is False
        assert coord.fences == 1
        # An epoch never granted is equally stale.
        assert coord._accept_delta(seg, "ffffffff-100000000", 5, body) is False
        # The exact live epoch merges exactly once...
        assert coord._accept_delta(seg, part, 5, body) is True
        assert coord._results[(seg, part)] is body
        # ...and its duplicate is fenced, even at the same epoch.
        assert coord._accept_delta(seg, part, 5, body) is False
        assert coord.fences == 3
        assert coord.stale_accepted == 0
    finally:
        coord._pool.shutdown(wait=False)
        led.close()
    cold = read_grants_cold(str(tmp_path / "state"))
    assert cold is not None
    assert cold["searches"]["s-unit"]["fences"] == 3


def test_coordinator_epoch_floor_monotone():
    coord = Coordinator(search="s", nodes=lambda: [], epoch_floor=41)
    try:
        assert coord._next_epoch() == 42  # restart fences the dead boot
        assert coord._next_epoch() == 43
    finally:
        coord._pool.shutdown(wait=False)


# -- grant ledger durability --------------------------------------------------


def _seed_ledger(directory: str) -> GrantLedger:
    led = GrantLedger(directory)
    led.search(search="s1", segs=2, parts=2)
    led.grant(search="s1", seg="k1", part="p1", epoch=1, node="a", reason="grant")
    led.grant(search="s1", seg="k1", part="p2", epoch=2, node="b", reason="grant")
    led.delta(
        search="s1", seg="k1", part="p1", epoch=1, node="a",
        verdict=0, states=3, size=64,
    )
    led.done(search="s1", seg="k1", part="p1", epoch=1, reason="done")
    return led


def test_grant_ledger_torn_tail_recovers_valid_prefix(tmp_path):
    directory = str(tmp_path / "ledger")
    _seed_ledger(directory).close()
    # Tear the tail: the coordinator died mid-append of the ``done``
    # record, leaving a valid header and a truncated payload.
    segs = sorted(p for p in os.listdir(directory) if p.startswith("seg-"))
    path = os.path.join(directory, segs[-1])
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    led = GrantLedger(directory)
    orphans, floors = led.recover()
    # The torn ``done`` is dropped, so p1's grant is open again — exactly
    # the honest reading: its closure never durably happened.
    assert sorted(o["part"] for o in orphans) == ["p1", "p2"]
    assert floors == {"s1": 2}
    assert led.recovery.torn_tail_bytes > 0
    assert led.recovery.records == 4
    # The writer rotates away from the damaged segment; new records land
    # and survive the next recovery.
    led.done(search="s1", seg="k1", part="p2", epoch=2, reason="done")
    led.close()
    led2 = GrantLedger(directory)
    orphans2, _ = led2.recover()
    assert sorted(o["part"] for o in orphans2) == ["p1"]
    led2.close()


def test_grant_ledger_recover_clean(tmp_path):
    directory = str(tmp_path / "ledger")
    led = _seed_ledger(directory)
    orphans, floors = led.recover()
    assert [o["part"] for o in orphans] == ["p2"]  # p1 closed by done
    assert floors == {"s1": 2}
    led.verdict(search="s1", verdict=0, outcome="ok")
    orphans, _ = led.recover()
    assert orphans == []  # a verdict closes every record of the search
    led.close()


def test_read_grants_cold_absent_and_present(tmp_path):
    empty = tmp_path / "no-ledger"
    empty.mkdir()
    assert read_grants_cold(str(empty)) is None
    state = tmp_path / "state"
    _seed_ledger(str(state / GRANTS_SUBDIR)).close()
    cold = read_grants_cold(str(state))
    s = cold["searches"]["s1"]
    assert s["verdict"] is None  # live at death
    assert [g["part"] for g in s["open_grants"]] == ["p2"]
    assert s["last_delta"]["p1"]["verdict"] == 0
    assert cold["open_total"] == 1
    assert cold["recovery"]["torn_tail_bytes"] == 0


# -- end-to-end: the coordinated route ---------------------------------------


def _backend_cfg(tmp_path, name: str) -> VerifydConfig:
    return VerifydConfig(
        socket_path=str(tmp_path / f"{name}.sock"),
        workers=1,
        device="off",
        no_viz=True,
        stats_log=None,
        out_dir=str(tmp_path / f"viz-{name}"),
    )


def _router_cfg(tmp_path, names, **overrides) -> RouterConfig:
    kw = dict(
        listen=str(tmp_path / "router.sock"),
        backends=tuple(
            BackendSpec(n, str(tmp_path / f"{n}.sock")) for n in names
        ),
        probe_interval_s=30.0,
        state_dir=str(tmp_path / "router-state"),
    )
    kw.update(overrides)
    return RouterConfig(**kw)


def test_distributed_submit_verdict_parity_ok(tmp_path):
    text = _text(_branchy(rounds=3, k=2, base=900))
    hist = prepare(list(ev.iter_history(text)), elide_trivial=True)
    oracle = check_frontier_auto(hist)
    assert oracle.outcome == CheckOutcome.OK
    names = ("a", "b", "c")
    with Verifyd(_backend_cfg(tmp_path, "a")), Verifyd(
        _backend_cfg(tmp_path, "b")
    ), Verifyd(_backend_cfg(tmp_path, "c")), VerifydRouter(
        _router_cfg(tmp_path, names)
    ) as router:
        client = VerifydClient(router.cfg.listen)
        reply = client.submit(text, no_viz=True, distributed=True)
        assert reply["verdict"] == 0
        assert reply["outcome"] == "ok"
        assert reply["distributed"] is True
        assert reply["node"] == "distributed"
        assert reply["stale_accepted"] == 0
        # Three segments; the first carries only INIT, later boundaries
        # carry a branched union split across nodes.
        assert reply["partitions"] >= 3
        assert reply["grants"] >= reply["partitions"]
        assert set(reply["owners"].values()) <= set(names)
        snap = client.stats()
        assert snap["distsearch"]["searches"] == 1
        assert snap["distsearch"]["ledger"] is True
    # The ledger closed the search: nothing left open post-mortem.
    cold = read_grants_cold(str(tmp_path / "router-state"))
    assert cold is not None and cold["open_total"] == 0
    (search_rec,) = cold["searches"].values()
    assert search_rec["verdict"] == 0 and search_rec["outcome"] == "ok"


def test_distributed_submit_verdict_parity_illegal(tmp_path):
    h = _branchy(rounds=2, k=2, base=1300)
    h.check_tail_ok(99, tail=50)  # impossible: at most 2 records applied
    text = _text(h)
    hist = prepare(list(ev.iter_history(text)), elide_trivial=True)
    assert check_frontier_auto(hist).outcome == CheckOutcome.ILLEGAL
    with Verifyd(_backend_cfg(tmp_path, "a")), Verifyd(
        _backend_cfg(tmp_path, "b")
    ), VerifydRouter(_router_cfg(tmp_path, ("a", "b"))) as router:
        client = VerifydClient(router.cfg.listen)
        reply = client.submit(text, no_viz=True, distributed=True)
        assert reply["verdict"] == 1
        assert reply["outcome"] == "illegal"
        assert reply["distributed"] is True
        assert reply["stale_accepted"] == 0


def test_distributed_falls_back_on_single_backend(tmp_path):
    text = _text(_branchy(rounds=2, k=2, base=1700))
    with Verifyd(_backend_cfg(tmp_path, "a")), VerifydRouter(
        _router_cfg(tmp_path, ("a",))
    ) as router:
        client = VerifydClient(router.cfg.listen)
        # One healthy node can't host a fleet search: the route degrades
        # to the plain single-node submit — correct, just not parallel.
        reply = client.submit(text, no_viz=True, distributed=True)
        assert reply["verdict"] == 0
        assert not reply.get("distributed")
        assert reply["node"] == "a"
        assert client.stats()["distsearch"]["fallbacks"] == 1
