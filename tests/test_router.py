"""Router tier: hash ring, breaker, prober, routing, drain, deadline.

Unit layers run clockless (the breaker takes an injected clock, the
ring is pure); the end-to-end layer boots two in-process ``Verifyd``
backends (device off, one worker) behind an in-process
``VerifydRouter`` on unix sockets — affinity, the edge cache, failover
off a dead home node, the drain/undrain protocol, NoBackend when the
fleet is gone, and the client's ``--deadline`` budget are all pinned
here so ``make fleet`` (scripts/fleet_check.py) only has to prove the
multi-process/SIGKILL story.
"""

import io

import pytest

from s2_verification_tpu.obs.probe import CircuitBreaker, HealthProber
from s2_verification_tpu.service.cache import history_fingerprint
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.service.client import (
    VerifydClient,
    VerifydDeadlineExceeded,
    VerifydError,
)
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.router import (
    BackendSpec,
    HashRing,
    RouterConfig,
    VerifydRouter,
)
from s2_verification_tpu.utils import events as ev

from helpers import H, fold


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history(base: int = 100) -> str:
    h = H()
    h.append_ok(1, [base + 1], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([base + 1]))
    return _text(h)


def bad_history(base: int = 100) -> str:
    h = H()
    h.append_ok(1, [base + 1], tail=1)
    h.read_ok(2, tail=1, stream_hash=base)  # impossible stream hash
    return _text(h)


# -- hash ring ----------------------------------------------------------------


def test_ring_deterministic_and_complete():
    ring = HashRing(["a", "b", "c"], replicas=64)
    keys = [f"v1:{i:016x}:4" for i in range(200)]
    owners = {k: ring.lookup(k) for k in keys}
    assert set(owners.values()) == {"a", "b", "c"}  # all nodes own keys
    again = HashRing(["c", "a", "b"], replicas=64)  # order-independent
    assert {k: again.lookup(k) for k in keys} == owners


def test_ring_remove_remaps_only_the_lost_nodes_keys():
    ring = HashRing(["a", "b", "c"], replicas=64)
    keys = [f"v1:{i:016x}:4" for i in range(300)]
    before = {k: ring.lookup(k) for k in keys}
    ring.remove("b")
    after = {k: ring.lookup(k) for k in keys}
    for k in keys:
        if before[k] != "b":
            # Stability: a surviving node's keys never move.
            assert after[k] == before[k]
        else:
            assert after[k] in ("a", "c")


def test_ring_add_restores_ownership():
    ring = HashRing(["a", "b", "c"], replicas=64)
    keys = [f"v1:{i:016x}:4" for i in range(300)]
    before = {k: ring.lookup(k) for k in keys}
    ring.remove("b")
    ring.add("b")
    assert {k: ring.lookup(k) for k in keys} == before


def test_ring_preference_is_home_first_all_distinct():
    ring = HashRing(["a", "b", "c"], replicas=64)
    pref = ring.preference("some-fingerprint")
    assert sorted(pref) == ["a", "b", "c"]
    assert pref[0] == ring.lookup("some-fingerprint")


def test_ring_empty_and_bad_replicas():
    assert HashRing().lookup("x") is None
    assert HashRing().preference("x") == []
    with pytest.raises(ValueError):
        HashRing(replicas=0)


# -- circuit breaker (injected clock — no sleeping) ---------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_consecutive_failures():
    clk = _Clock()
    br = CircuitBreaker(failures=3, reset_s=5.0, time_fn=clk)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # under threshold
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # success reset the streak
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()


def test_breaker_half_open_probe_single_slot():
    clk = _Clock()
    br = CircuitBreaker(failures=1, reset_s=5.0, time_fn=clk)
    br.record_failure()
    assert br.state == "open"
    clk.t = 4.9
    assert not br.allow()
    clk.t = 5.1
    assert br.allow()  # the single half-open probe
    assert br.state == "half_open"
    assert not br.allow()  # concurrent caller refused
    br.record_success()
    assert br.state == "closed"
    assert br.allow()


def test_breaker_failed_probe_reopens_with_fresh_window():
    clk = _Clock()
    br = CircuitBreaker(failures=1, reset_s=5.0, time_fn=clk)
    br.record_failure()
    clk.t = 6.0
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == "open"
    clk.t = 10.0  # 4s into the NEW window
    assert not br.allow()
    clk.t = 11.1
    assert br.allow()
    br.record_success()
    assert br.state == "closed"


def test_breaker_reset_forces_closed():
    br = CircuitBreaker(failures=1, reset_s=1000.0, time_fn=lambda: 0.0)
    br.record_failure()
    assert br.state == "open"
    br.reset()
    assert br.state == "closed" and br.allow()
    with pytest.raises(ValueError):
        CircuitBreaker(failures=0)


# -- health prober (synchronous ticks, fake probes) ---------------------------


def test_prober_reports_first_observation_and_transitions():
    up = {"a": True, "b": False}
    changes = []
    prober = HealthProber(
        {n: (lambda n=n: up[n]) for n in up},
        on_change=lambda name, ok: changes.append((name, ok)),
    )
    assert prober.probe_once() == {"a": True, "b": False}
    assert sorted(changes) == [("a", True), ("b", False)]  # first obs fires
    changes.clear()
    prober.probe_once()
    assert changes == []  # steady state is silent
    up["b"] = True
    prober.probe_once()
    assert changes == [("b", True)]
    assert prober.status == {"a": True, "b": True}


def test_prober_raising_probe_reads_down():
    def boom():
        raise OSError("probe exploded")

    prober = HealthProber({"x": boom})
    assert prober.probe_once() == {"x": False}
    assert prober.status["x"] is False


# -- backend spec -------------------------------------------------------------


def test_backend_spec_parse():
    s = BackendSpec.parse("a=/tmp/a.sock")
    assert (s.name, s.address, s.healthz_url) == ("a", "/tmp/a.sock", None)
    s = BackendSpec.parse("b=127.0.0.1:7000@http://127.0.0.1:9000/healthz")
    assert s.address == "127.0.0.1:7000"
    assert s.healthz_url == "http://127.0.0.1:9000/healthz"
    for bad in ("no-equals", "=addr", "name="):
        with pytest.raises(ValueError):
            BackendSpec.parse(bad)


# -- end-to-end topology helpers ----------------------------------------------


def _backend_cfg(tmp_path, name: str) -> VerifydConfig:
    return VerifydConfig(
        socket_path=str(tmp_path / f"{name}.sock"),
        workers=1,
        device="off",
        no_viz=True,
        stats_log=None,
        out_dir=str(tmp_path / f"viz-{name}"),
    )


def _router_cfg(tmp_path, names, **overrides) -> RouterConfig:
    kw = dict(
        listen=str(tmp_path / "router.sock"),
        backends=tuple(
            BackendSpec(n, str(tmp_path / f"{n}.sock")) for n in names
        ),
        probe_interval_s=30.0,  # tests drive probe_once() themselves
        breaker_failures=2,
        breaker_reset_s=0.2,
        max_failovers=2,
    )
    kw.update(overrides)
    return RouterConfig(**kw)


def _fingerprint(text: str) -> str:
    return history_fingerprint(
        prepare(list(ev.iter_history(text)), elide_trivial=True)
    )


def _ring_key(text: str) -> str:
    """The router's placement key: prefix affinity, not fingerprint."""
    hist = prepare(list(ev.iter_history(text)), elide_trivial=True)
    return VerifydRouter._affinity_key(hist, history_fingerprint(hist))


def _homed_at(router: VerifydRouter, node: str, base: int = 10_000) -> str:
    """A fresh linearizable history whose ring home is ``node``."""
    while True:
        base += 1000
        text = good_history(base)
        if router.ring.preference(_ring_key(text))[0] == node:
            return text


def test_router_affinity_cache_and_fleet_view(tmp_path):
    with Verifyd(_backend_cfg(tmp_path, "a")), Verifyd(
        _backend_cfg(tmp_path, "b")
    ), VerifydRouter(_router_cfg(tmp_path, ("a", "b"))) as router:
        client = VerifydClient(router.cfg.listen)
        assert client.ping()["server"] == "verifyd-router"

        texts = {0: good_history(100), 1: bad_history(200)}
        first = {v: client.submit(t, no_viz=True) for v, t in texts.items()}
        for verdict, reply in first.items():
            assert reply["verdict"] == verdict
            assert reply["node"] == router.ring.lookup(
                _ring_key(texts[verdict])
            )
            assert not reply.get("cached")
        # Duplicate: answered from the router's edge cache, provenance
        # (the home node) preserved.
        for verdict, text in texts.items():
            again = client.submit(text, no_viz=True)
            assert again["verdict"] == verdict
            assert again["cached"] and again["router_cached"]
            assert again["node"] == first[verdict]["node"]

        fleet = client.fleet()
        assert fleet["ring"]["nodes"] == ["a", "b"]
        assert [b["name"] for b in fleet["backends"]] == ["a", "b"]
        assert all(not b["draining"] for b in fleet["backends"])

        snap = client.stats()
        assert snap["routed"] == 2 and snap["cache_hits"] == 2
        assert "slo" in snap and "metrics" in snap


def test_router_failover_when_home_dies(tmp_path):
    backend_a = Verifyd(_backend_cfg(tmp_path, "a")).__enter__()
    try:
        with Verifyd(_backend_cfg(tmp_path, "b")), VerifydRouter(
            _router_cfg(tmp_path, ("a", "b"))
        ) as router:
            client = VerifydClient(router.cfg.listen)
            text = _homed_at(router, "a")
            backend_a.__exit__(None, None, None)  # the home node dies
            reply = client.submit(text, no_viz=True)
            assert reply["verdict"] == 0
            assert reply["node"] == "b"  # failed over, job not lost
            assert client.stats()["failovers"] >= 1
    finally:
        # Idempotent: already exited inside the happy path.
        backend_a.request_stop()


def test_router_drain_undrain_protocol(tmp_path):
    with Verifyd(_backend_cfg(tmp_path, "a")), Verifyd(
        _backend_cfg(tmp_path, "b")
    ), VerifydRouter(_router_cfg(tmp_path, ("a", "b"))) as router:
        client = VerifydClient(router.cfg.listen)
        text = _homed_at(router, "a")
        drain = client.drain("a", drain_timeout_s=5.0, timeout=None)
        assert drain["node"] == "a" and drain["drained"]
        fleet = {b["name"]: b for b in client.fleet()["backends"]}
        assert fleet["a"]["draining"]
        # A fresh history homed at the drained node routes around it.
        reply = client.submit(text, no_viz=True)
        assert reply["verdict"] == 0 and reply["node"] == "b"
        # Unknown node: a semantic error, not a crash.
        with pytest.raises(VerifydError):
            client.drain("nope")
        client.undrain("a")
        fleet = {b["name"]: b for b in client.fleet()["backends"]}
        assert not fleet["a"]["draining"]


def test_router_no_backend_when_fleet_is_gone(tmp_path):
    cfg = _backend_cfg(tmp_path, "a")
    with Verifyd(cfg):
        pass  # boots and exits: the socket path is gone
    with VerifydRouter(_router_cfg(tmp_path, ("a",))) as router:
        router.prober.probe_once()
        client = VerifydClient(router.cfg.listen)
        with pytest.raises(VerifydError) as ei:
            client.submit(good_history(300), no_viz=True)
        assert ei.value.cls == "NoBackend"
        assert client.stats()["no_backend"] == 1


def test_router_decode_error_answered_at_the_edge(tmp_path):
    with Verifyd(_backend_cfg(tmp_path, "a")), VerifydRouter(
        _router_cfg(tmp_path, ("a",))
    ) as router:
        client = VerifydClient(router.cfg.listen)
        with pytest.raises(VerifydError) as ei:
            client.submit("not json at all\n", no_viz=True)
        assert ei.value.cls == "DecodeError"
        assert client.stats()["decode_errors"] == 1
        assert client.stats()["routed"] == 0  # no backend burned a slot


def test_router_bad_priority_is_decode_error_not_internal(tmp_path):
    """A non-numeric client-supplied priority answers ``DecodeError`` at
    the edge — the daemon's contract for the same input — instead of a
    ``ValueError`` escaping the route and surfacing as InternalError
    from the dispatch catch-all."""
    with VerifydRouter(_router_cfg(tmp_path, ("a",))) as router:
        for route in (router._route_submit, router._route_follow):
            reply = route(
                {
                    "history": good_history(7),
                    "stream": "s",
                    "priority": "urgent",
                }
            )
            e = reply.get("err")
            assert e is not None and e["class"] == "DecodeError", reply
            assert "priority" in e["msg"]


# -- submit --deadline --------------------------------------------------------


def test_deadline_exceeded_raises_with_budget_and_attempts(tmp_path):
    client = VerifydClient(str(tmp_path / "nothing-listens-here.sock"))
    with pytest.raises(VerifydDeadlineExceeded) as ei:
        client.submit_with_retry(
            good_history(), retries=50, backoff_s=0.05, deadline_s=0.4
        )
    e = ei.value
    assert e.deadline_s == 0.4
    assert e.attempts >= 1
    assert f"deadline exceeded after {e.attempts} attempts" in str(e)
    # The budget is honored as a VerifydUnavailable subtype: exit 69.
    from s2_verification_tpu.service.client import VerifydUnavailable

    assert isinstance(e, VerifydUnavailable)


def test_deadline_none_keeps_plain_unavailable(tmp_path):
    from s2_verification_tpu.service.client import VerifydUnavailable

    client = VerifydClient(str(tmp_path / "nothing-listens-here.sock"))
    with pytest.raises(VerifydUnavailable) as ei:
        client.submit_with_retry(good_history(), retries=1, backoff_s=0.01)
    assert not isinstance(ei.value, VerifydDeadlineExceeded)
