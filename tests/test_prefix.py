"""Incremental prefix verification: frontier cache, follow mode, parity.

The soundness contract under test: a warm search resumed from a cached
chain-hash frontier must be *verdict-equivalent* to the cold search of
the same history — across legal shapes, every ground-truth violation
class, and an illegal suffix appended after an OK cached prefix.  Plus
the safety rails: snapshots only at prefix-closed boundaries, window
verdicts never leak into fingerprint-global caches, and the on-disk
store recovers through torn tails.
"""

import glob
import io
import json
import os

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.prefix import PrefixCarry, has_open_ops
from s2_verification_tpu.collector.campaign import (
    Campaign,
    CampaignPhase,
    collect_labeled,
)
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from s2_verification_tpu.models.stream import StreamState
from s2_verification_tpu.service.cache import history_fingerprint
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.prefixstore import (
    PREFIX_SUBDIR,
    PrefixStore,
    make_entry,
    plan_for_submit,
    prefix_accumulators,
    read_cold,
)
from s2_verification_tpu.service.protocol import ERR_DECODE, ERR_FRONTIER
from s2_verification_tpu.service.router import (
    BackendSpec,
    RouterConfig,
    VerifydRouter,
)
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

# -- fixtures ----------------------------------------------------------------

_QUIET = FaultPlan(min_latency=0.001, max_latency=0.003)


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def serial_lines(n_ops: int, seed: int = 0) -> list[str]:
    """A serial single-client all-OK history (2 JSONL lines per op):
    every op boundary is a closed cut."""
    h = H()
    hashes: list[int] = []
    for k in range(n_ops):
        if k % 2 == 0:
            hashes.append(1000 + k + seed)
            h.append_ok(1, [hashes[-1]], tail=len(hashes))
        else:
            h.read_ok(1, tail=len(hashes), stream_hash=fold(hashes))
    return [ln for ln in _text(h).splitlines() if ln.strip()]


def _join(lines: list[str]) -> str:
    return "\n".join(lines) + "\n"


def _prep(text: str):
    return prepare(list(ev.iter_history(text)), elide_trivial=True)


def _daemon_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        out_dir=str(tmp_path / "viz"),
        stats_log=str(tmp_path / "stats.jsonl"),
        no_viz=True,
        prefix_enabled=True,
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _stats_events(tmp_path) -> list[dict]:
    with open(tmp_path / "stats.jsonl", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _closed_cut(lines: list[str], frac: float = 0.6) -> int:
    """Line index nearest ``frac`` through the stream where no call is
    open — an event cut no op spans (0 when none exists)."""
    open_ops: set = set()
    cuts = []
    for i, line in enumerate(lines):
        le = ev.decode_obj(json.loads(line))
        if le.is_start:
            open_ops.add((le.client_id, le.op_id))
        else:
            open_ops.discard((le.client_id, le.op_id))
        if not open_ops:
            cuts.append(i + 1)
    interior = [c for c in cuts if 0 < c < len(lines)]
    if not interior:
        return 0
    target = frac * len(lines)
    return min(interior, key=lambda c: abs(c - target))


def _campaign(cls: str | None, workflow: str = "regular") -> Campaign:
    phases = (
        (CampaignPhase("steady", 1.0, faults=_QUIET),)
        if cls is None
        else (
            CampaignPhase("warm", 0.02, faults=_QUIET),
            CampaignPhase("violate", 1.0, faults=_QUIET, violation=cls),
        )
    )
    name = f"t-{cls or 'legal'}-{workflow}"
    return Campaign(
        name=name, workflow=workflow, clients=3, ops=16, phases=phases
    )


# -- boundary soundness (unit) ----------------------------------------------


def test_plan_refuses_snapshot_across_open_ops():
    """A pending call at the end of the history: the geometric K = n
    boundary must not be snapshotted (its outcome is undecided), and the
    plan says why."""
    h = H()
    h.append_ok(1, [1], tail=1)
    h.read_ok(1, tail=1, stream_hash=fold([1]))
    h.append_ok(1, [2], tail=2)
    h.read_ok(1, tail=2, stream_hash=fold([1, 2]))
    h.call_append(2, [3])  # never finishes
    hist = prepare(h.events)
    assert has_open_ops(hist)
    store = PrefixStore(capacity=8)
    plan = plan_for_submit(store, hist, min_ops=2)
    assert plan is not None
    assert plan.refused == "open_ops"
    assert len(hist.ops) not in plan.snap_keys


def test_store_refuses_malformed_entries():
    store = PrefixStore(capacity=8)
    with pytest.raises(ValueError):
        store.put("pv2:0:1", {"n": 1, "s": []})  # empty carried state set
    with pytest.raises(ValueError):
        PrefixCarry.from_payload({"n": 1, "s": []})


def test_affinity_key_stable_under_extension():
    """The router's ring key for a history and for its extension agree —
    the whole lineage homes on the node holding the snapshots — while
    distinct streams separate."""
    short = _prep(_join(serial_lines(12)))
    long = _prep(_join(serial_lines(40)))
    other = _prep(_join(serial_lines(12, seed=7)))
    k_short = VerifydRouter._affinity_key(short, history_fingerprint(short))
    k_long = VerifydRouter._affinity_key(long, history_fingerprint(long))
    k_other = VerifydRouter._affinity_key(other, history_fingerprint(other))
    assert k_short == k_long
    assert k_short != k_other


# -- warm vs cold parity -----------------------------------------------------

_PARITY_CASES = [
    ("legal-serial-appends", None, None),
    ("legal-serial-mixed", None, None),
    ("legal-regular", None, "regular"),
    ("legal-match-seq-num", None, "match-seq-num"),
    ("legal-fencing", None, "fencing"),
    ("violation-drop_acked", "drop_acked", "regular"),
    ("violation-reorder", "reorder", "regular"),
    ("violation-stale_read", "stale_read", "regular"),
    ("violation-fence_resurrect", "fence_resurrect", "fencing"),
]


def _parity_text(name: str, cls: str | None, workflow: str | None):
    """(history text, expected verdict) for one parity case."""
    if workflow is None:
        if name.endswith("appends"):
            h = H()
            for k in range(12):
                h.append_ok(1, [100 + k], tail=k + 1)
            return _text(h), 0
        return _join(serial_lines(16)), 0
    events, label = collect_labeled(_campaign(cls, workflow), seed=11)
    if cls is not None:
        assert label["fired"] and label["confirmed"], name
        assert label["expect"] == "illegal", name
    buf = io.StringIO()
    ev.write_history(events, buf)
    return buf.getvalue(), 0 if cls is None else 1


def test_warm_vs_cold_verdict_parity(tmp_path):
    """The acceptance gate: for five legal shapes and all four
    ground-truth violation classes, a daemon whose store was warmed with
    a committed prefix answers the full history with the *identical*
    verdict a prefix-less daemon computes cold."""
    warm_dir = tmp_path / "warm"
    cold_dir = tmp_path / "cold"
    warm_dir.mkdir()
    cold_dir.mkdir()
    warm_cfg = _daemon_cfg(warm_dir)
    cold_cfg = _daemon_cfg(cold_dir, prefix_enabled=False)
    resumed = 0
    with Verifyd(warm_cfg), Verifyd(cold_cfg):
        warm = VerifydClient(warm_cfg.socket_path, timeout=120)
        cold = VerifydClient(cold_cfg.socket_path, timeout=120)
        for name, cls, workflow in _PARITY_CASES:
            text, expected = _parity_text(name, cls, workflow)
            lines = [ln for ln in text.splitlines() if ln.strip()]
            cut = _closed_cut(lines)
            if cut:
                # Commit the prefix: OK prefixes snapshot their frontier.
                warm.submit(_join(lines[:cut]), no_viz=True)
            warm_reply = warm.submit(text, no_viz=True)
            cold_reply = cold.submit(text, no_viz=True)
            assert warm_reply["verdict"] == expected, name
            assert cold_reply["verdict"] == expected, name
            assert warm_reply["verdict"] == cold_reply["verdict"], name
            assert warm_reply["outcome"] == cold_reply["outcome"], name
            assert warm_reply["ops"] == cold_reply["ops"], name
            assert not cold_reply["backend"].startswith("frontier-resume")
            if warm_reply["backend"].startswith("frontier-resume"):
                resumed += 1
    # The parity above would pass vacuously if nothing ever resumed.
    assert resumed >= 2


def test_illegal_suffix_after_cached_ok_prefix(tmp_path):
    """An OK prefix is committed and cached; a later submission extends
    it with a violating suffix.  The warm search must still answer
    ILLEGAL — resuming from the frontier skips re-deciding the prefix,
    never the suffix."""
    h = H()
    hashes = []
    for k in range(24):
        if k % 2 == 0:
            hashes.append(1000 + k)
            h.append_ok(1, [hashes[-1]], tail=len(hashes))
        else:
            h.read_ok(1, tail=len(hashes), stream_hash=fold(hashes))
    prefix_text = _text(h)
    h.read_ok(2, tail=999, stream_hash=424242)  # unjustifiable read
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        assert client.submit(prefix_text, no_viz=True)["verdict"] == 0
        reply = client.submit(_text(h), no_viz=True)
        assert reply["verdict"] == 1
        assert reply["backend"].startswith("frontier-resume")
    hits = [e for e in _stats_events(tmp_path) if e.get("ev") == "prefix_hit"]
    assert hits and hits[-1]["resume_ops"] > 0


# -- the store on disk -------------------------------------------------------


def test_prefix_store_survives_torn_tail(tmp_path):
    """A daemon killed mid-append leaves a torn record; recovery drops
    exactly the tail and keeps every intact snapshot."""
    d = str(tmp_path / PREFIX_SUBDIR)
    hist = _prep(_join(serial_lines(8)))
    keys = prefix_accumulators(hist)
    store = PrefixStore(capacity=16, persist_dir=d)
    for k in sorted(keys):
        carry = PrefixCarry(
            ops=k,
            states=(StreamState(tail=k, stream_hash=0, fencing_token=None),),
        )
        store.put(keys[k], make_entry(carry, events=2 * k))
    n = len(store)
    assert n >= 2
    store.close()
    seg = sorted(glob.glob(os.path.join(d, "seg-*.log")))[-1]
    with open(seg, "ab") as f:
        f.write(b"\x00\x01torn")  # mid-append death
    reopened = PrefixStore(capacity=16, persist_dir=d)
    assert len(reopened) == n
    assert reopened.recovery is not None
    assert reopened.recovery.torn_tail_bytes > 0
    reopened.close()
    cold = read_cold(str(tmp_path))
    assert cold is not None
    assert cold["entries"] == n
    assert cold["recovery"]["torn_tail_bytes"] > 0
    assert cold["deepest_ops"] == max(keys)


# -- follow mode -------------------------------------------------------------


def test_follow_end_to_end_restart_and_cross_lineage(tmp_path):
    """The full monitoring story: windows advance a frontier, the
    lineage survives a daemon restart (same --state-dir), a full-history
    submit resumes from snapshots a *follow* lineage wrote, and an
    unknown token is a definite error."""
    lines = serial_lines(60)  # 120 JSONL lines, 20 ops per 40-line window
    state = str(tmp_path / "state")
    cfg = _daemon_cfg(tmp_path, state_dir=state)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        r1 = client.follow(_join(lines[:40]), stream="orders")
        assert r1["verdict"] == 0 and r1["scope"] == "window"
        assert r1["advanced"] and r1["window"] == 0
        assert r1["ops_total"] == 20
        token = r1["frontier"]
        assert token.startswith("pv")
        r2 = client.follow(_join(lines[40:80]), stream="orders", frontier=token)
        assert r2["verdict"] == 0 and r2["window"] == 1
        assert r2["ops_total"] == 40
        assert r2["backend"].startswith("frontier-resume")
        token = r2["frontier"]
        # Cross-lineage: the cumulative history arrives as one submit —
        # the chain-hash keys the follow windows wrote must answer it.
        full = client.submit(_join(lines[:80]), no_viz=True)
        assert full["verdict"] == 0
        assert full["backend"].startswith("frontier-resume")
    # Reboot on the same state dir: the frontier token still resolves.
    cfg2 = _daemon_cfg(tmp_path, state_dir=state)
    with Verifyd(cfg2):
        client = VerifydClient(cfg2.socket_path, timeout=120)
        r3 = client.follow(_join(lines[80:120]), stream="orders", frontier=token)
        assert r3["verdict"] == 0 and r3["ops_total"] == 60
        assert r3["backend"].startswith("frontier-resume")
        with pytest.raises(VerifydError) as exc:
            client.follow(
                _join(lines[:2]),
                stream="orders",
                frontier="pv2:00000000deadbeef:4",
            )
        assert exc.value.cls == ERR_FRONTIER
    names = [e.get("ev") for e in _stats_events(tmp_path)]
    assert "prefix_loaded" in names  # second boot replayed the log
    assert "window_done" in names
    assert "prefix_snapshot" in names


def test_follow_catches_violation_in_window(tmp_path):
    lines = serial_lines(20)
    bad = H()
    bad.read_ok(1, tail=1, stream_hash=99999)
    bad_lines = [ln for ln in _text(bad).splitlines() if ln.strip()]
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        r1 = client.follow(_join(lines), stream="s")
        assert r1["verdict"] == 0
        r2 = client.follow(
            _join(bad_lines), stream="s", frontier=r1["frontier"]
        )
        assert r2["verdict"] == 1
        assert not r2["advanced"]  # an illegal window never commits
        assert r2["frontier"] == r1["frontier"]  # carried, not advanced


def test_follow_open_window_and_missing_store(tmp_path):
    """A window with a dangling call still gets a verdict but the
    frontier must not advance past the undecided op; a daemon without
    the prefix store refuses the op outright."""
    h = H()
    h.append_ok(1, [1], tail=1)
    h.read_ok(1, tail=1, stream_hash=fold([1]))
    h.append_ok(1, [2], tail=2)
    h.read_ok(1, tail=2, stream_hash=fold([1, 2]))
    h.call_append(2, [3])  # dangling call spans the window edge
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        r = client.follow(_text(h), stream="s")
        assert r["verdict"] == 0
        assert not r["advanced"]
        assert r["frontier"] is None  # lineage never started
    nostore = tmp_path / "nostore"
    nostore.mkdir()
    cfg2 = _daemon_cfg(nostore, prefix_enabled=False)
    with Verifyd(cfg2):
        client = VerifydClient(cfg2.socket_path, timeout=120)
        with pytest.raises(VerifydError) as exc:
            client.follow(_join(serial_lines(8)), stream="s")
        assert exc.value.cls == ERR_DECODE


# -- window verdicts stay window-scoped --------------------------------------


def test_window_verdict_never_enters_verdict_cache(tmp_path):
    """A window OK'd under a carried frontier describes *stream-so-far*,
    not the window text standalone — the same text submitted cold must
    get a fresh search, not a cache answer."""
    lines = serial_lines(40)  # 80 JSONL lines
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        r1 = client.follow(_join(lines[:40]), stream="s")
        window2 = _join(lines[40:])
        r2 = client.follow(window2, stream="s", frontier=r1["frontier"])
        assert r2["verdict"] == 0  # OK given the carried prefix
        standalone = client.submit(window2, no_viz=True)
        assert not standalone.get("cached")
        # Standalone, the suffix window is NOT linearizable (its reads
        # observe appends committed in the prefix) — exactly why the
        # window verdict must never answer a fingerprint-global lookup.
        assert standalone["verdict"] == 1


def _follow_args(path, **overrides):
    import argparse

    kw = dict(
        file=str(path),
        socket="/tmp/nonexistent.sock",
        secret_file=None,
        stream="s",
        frontier=None,
        window=10,
        client="cli",
        priority=10,
        timeout=None,
        deadline=None,
        window_retries=2,
        stats=False,
    )
    kw.update(overrides)
    return argparse.Namespace(**kw)


def test_follow_cli_uncarried_window_retries_then_stops(tmp_path, monkeypatch):
    """An inconclusive window (deadline expiry, refused snapshot) must be
    retried as a resync and, still uncarried, stop the follow with exit 2
    — committing it anyway would silently drop its ops from the verified
    lineage and let later windows report OK for a stream-so-far that
    never included them."""
    from s2_verification_tpu import cli

    lines = serial_lines(10)  # 20 JSONL lines -> two 10-event windows
    f = tmp_path / "s.jsonl"
    f.write_text(_join(lines))
    calls = []

    def fake_follow(
        self, history_text=None, *, records=None, stream, frontier=None, **kw
    ):
        calls.append((history_text, frontier))
        return {
            "verdict": 2,
            "outcome": "UNKNOWN",
            "ops": 5,
            "ops_total": 5,
            "advanced": False,
            "frontier": frontier,
            "backend": "b",
        }

    monkeypatch.setattr(VerifydClient, "follow", fake_follow)
    rc = cli._cmd_follow(_follow_args(f))
    assert rc == 2
    assert len(calls) == 3  # first try + 2 resync retries, then stop
    assert calls[1][1] is None and calls[2][1] is None  # resyncs start cold
    # The loop never moved past window 0: every attempt carried exactly
    # the uncarried window's lines (committed was still empty).
    assert all(text == _join(lines[:10]) for text, _ in calls)


def test_follow_cli_resync_recovers_uncarried_window(tmp_path, monkeypatch):
    """A window uncarried on the first try but carried by the resync
    commits normally, and the next window rides the resync's frontier
    with only its own new events."""
    from s2_verification_tpu import cli

    lines = serial_lines(10)
    f = tmp_path / "s.jsonl"
    f.write_text(_join(lines))
    calls = []

    def fake_follow(
        self, history_text=None, *, records=None, stream, frontier=None, **kw
    ):
        calls.append((history_text, frontier))
        n = len(calls)
        if n == 1:
            return {
                "verdict": 2,
                "outcome": "UNKNOWN",
                "ops": 5,
                "ops_total": 5,
                "advanced": False,
                "frontier": None,
                "backend": "b",
            }
        ops = history_text.count("\n") // 2
        return {
            "verdict": 0,
            "outcome": "OK",
            "ops": ops,
            "ops_total": ops,
            "advanced": True,
            "frontier": f"tok{n}",
            "backend": "b",
        }

    monkeypatch.setattr(VerifydClient, "follow", fake_follow)
    rc = cli._cmd_follow(_follow_args(f))
    assert rc == 0
    # window 0 try, window 0 resync (carried), window 1 on the new token
    assert [fr for _, fr in calls] == [None, None, "tok2"]
    assert calls[2][0] == _join(lines[10:])  # only the new events


def test_router_edge_cache_refuses_window_scope(tmp_path):
    """The router-side guard for the same rule: replies stamped
    ``scope=window`` never populate the fingerprint-keyed edge cache."""
    router = VerifydRouter(
        RouterConfig(
            listen=str(tmp_path / "r.sock"),
            backends=(BackendSpec("a", str(tmp_path / "a.sock")),),
        )
    )
    window_reply = {"verdict": 0, "scope": "window", "outcome": "OK"}
    router._cache_store(b"k1", "fp1", "aff1", window_reply)
    assert "fp1" not in router._verdicts
    full_reply = {"verdict": 0, "outcome": "OK"}
    router._cache_store(b"k2", "fp2", "aff2", full_reply)
    assert "fp2" in router._verdicts
