"""checker.diagnostics: refusal reports at the deepest configuration."""

from helpers import H, fold

from s2_verification_tpu.checker.diagnostics import deepest_refusals, derive_path
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.models.stream import INIT_STATE, READ, step_set


def _bad_read_history():
    """Two good appends, then a read whose stream hash no serialization
    can produce — the canonical refusing op."""
    h = H()
    h.append_ok(1, [111], tail=1)
    h.append_ok(1, [222], tail=2)
    h.read_ok(2, tail=2, stream_hash=99999)
    return prepare(h.events, elide_trivial=True)


def test_refusing_op_set_on_known_non_linearizable():
    hist = _bad_read_history()
    res = check(hist)
    assert res.outcome == CheckOutcome.ILLEGAL

    report = deepest_refusals(hist, res.deepest)
    assert report is not None
    order, refused = report

    # The deepest prefix is exactly the two appends, in program order...
    assert sorted(order) == sorted(res.deepest)
    appends = [op.index for op in hist.ops if op.inp.input_type != READ]
    assert sorted(order) == sorted(appends)
    # ...and the one op refusing to linearize there is the bogus read.
    (read_idx,) = [op.index for op in hist.ops if op.inp.input_type == READ]
    assert refused == [read_idx]


def test_derive_path_reaches_deepest_configuration():
    hist = _bad_read_history()
    res = check(hist)
    order, goal = derive_path(hist, res.deepest)
    assert order is not None and goal is not None

    # Replaying the derived order from INIT must be everywhere-legal and
    # land exactly on the goal state derive_path reports.
    states = [INIT_STATE]
    for j in order:
        op = next(o for o in hist.ops if o.index == j)
        states = step_set(states, op.inp, op.out)
        assert states, f"derived order illegal at op {j}"
    assert any(
        (s.tail, s.stream_hash, s.fencing_token)
        == (goal.tail, goal.stream_hash, goal.fencing_token)
        for s in states
    )
    # The configuration is the deepest one: both appends linearized.
    assert goal.tail == 2
    assert goal.stream_hash == fold([111, 222])


def test_non_prefix_deepest_yields_no_report():
    hist = _bad_read_history()
    # Client 1's second append without its first is not a per-chain prefix.
    appends = [op.index for op in hist.ops if op.inp.input_type != READ]
    not_a_prefix = [max(appends)]
    assert deepest_refusals(hist, not_a_prefix) is None
    assert derive_path(hist, not_a_prefix) == (None, None)


def test_empty_deepest_refuses_first_inconsistent_op():
    # Deepest = nothing linearized: every window-open candidate is tested
    # against INIT_STATE alone.
    h = H()
    h.read_ok(1, tail=7, stream_hash=12345)  # impossible from INIT
    hist = prepare(h.events, elide_trivial=True)
    res = check(hist)
    assert res.outcome == CheckOutcome.ILLEGAL
    report = deepest_refusals(hist, res.deepest or [])
    assert report is not None
    order, refused = report
    assert order == []
    assert refused == [hist.ops[0].index]
