"""Durable verifyd state: segment log, persistent verdict cache, and the
admission journal — the crash-safety contract under surgical corruption.

Everything here is CPU-only and in-process (the SIGKILL end of the
spectrum lives in ``scripts/chaos_bench.py`` / ``tests/test_chaos.py``):
the tests corrupt the on-disk bytes directly, which exercises the same
recovery paths a torn write would reach without needing a real crash.
"""

import io
import json
import os
import struct
import time

import pytest

from s2_verification_tpu.service.cache import VerdictCache, history_fingerprint
from s2_verification_tpu.service.client import VerifydClient
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.journal import JobJournal
from s2_verification_tpu.service.protocol import encode_frame
from s2_verification_tpu.utils import events as ev
from s2_verification_tpu.utils.seglog import SegmentLog

from helpers import H, fold

# -- fixtures (mirrors test_service.py) --------------------------------------


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    h.append_ok(2, [222, 333], tail=3)
    h.read_ok(1, tail=3, stream_hash=fold([111, 222, 333]))
    return _text(h)


def _daemon_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        no_viz=True,
        out_dir=str(tmp_path / "viz"),
        stats_log=str(tmp_path / "stats.jsonl"),
        state_dir=str(tmp_path / "state"),
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _segments(directory) -> list[str]:
    return sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.startswith("seg-")
    )


# -- segment log --------------------------------------------------------------


def test_seglog_round_trip_and_rotation(tmp_path):
    d = str(tmp_path / "log")
    log = SegmentLog(d, max_segment_bytes=64)
    payloads = [f"rec-{i}".encode() for i in range(20)]
    for p in payloads:
        log.append(p)
    log.close()
    assert len(_segments(d)) > 1  # 20 records cannot fit one 64-byte segment

    log2 = SegmentLog(d)
    assert log2.replay_all() == payloads
    rec = log2.recovery
    assert rec.records == 20 and rec.torn_tail_bytes == 0 and rec.bad_segments == 0
    log2.close()


def test_seglog_max_segments_drops_oldest(tmp_path):
    d = str(tmp_path / "log")
    log = SegmentLog(d, max_segment_bytes=64, max_segments=2)
    for i in range(30):
        log.append(f"rec-{i:04d}".encode())
    log.close()
    assert len(_segments(d)) <= 2
    replayed = SegmentLog(d).replay_all()
    # the newest records survive; the oldest aged out with their segment
    assert replayed and replayed[-1] == b"rec-0029"
    assert b"rec-0000" not in replayed


def test_seglog_torn_final_record_recovers_prefix(tmp_path):
    d = str(tmp_path / "log")
    log = SegmentLog(d)
    for i in range(5):
        log.append(f"rec-{i}".encode())
    log.close()
    seg = _segments(d)[-1]
    # tear mid-record: drop the last 3 bytes (a crashed write)
    with open(seg, "r+b") as f:
        f.truncate(os.path.getsize(seg) - 3)

    log2 = SegmentLog(d)
    assert log2.replay_all() == [f"rec-{i}".encode() for i in range(4)]
    rec = log2.recovery
    assert rec.torn_tail_bytes > 0 and rec.bad_segments == 0
    # appends after a torn tail go to a FRESH segment — the damaged file
    # is never extended past its valid prefix
    log2.append(b"after-tear")
    log2.close()
    assert len(_segments(d)) == 2
    assert SegmentLog(d).replay_all() == [
        b"rec-0",
        b"rec-1",
        b"rec-2",
        b"rec-3",
        b"after-tear",
    ]


def test_seglog_corrupted_record_drops_segment_tail(tmp_path):
    d = str(tmp_path / "log")
    log = SegmentLog(d)
    for i in range(5):
        log.append(f"rec-{i}".encode())
    log.close()
    seg = _segments(d)[-1]
    hdr = struct.calcsize("<II")
    rec_size = hdr + len(b"rec-0")
    # flip a payload byte inside record 2: its CRC fails, and nothing
    # past it in the segment can be trusted (lengths may be lies too)
    with open(seg, "r+b") as f:
        f.seek(2 * rec_size + hdr)
        b = f.read(1)
        f.seek(2 * rec_size + hdr)
        f.write(bytes([b[0] ^ 0xFF]))

    log2 = SegmentLog(d)
    assert log2.replay_all() == [b"rec-0", b"rec-1"]
    assert log2.recovery.dropped_records_possible
    log2.close()


# -- persistent verdict cache -------------------------------------------------


def test_verdict_cache_restart_round_trip(tmp_path):
    d = str(tmp_path / "verdicts")
    c = VerdictCache(capacity=16, persist_dir=d)
    c.put("fp-a", {"verdict": 0, "outcome": "ok"})
    c.put("fp-b", {"verdict": 1, "outcome": "illegal"})
    c.close()

    c2 = VerdictCache(capacity=16, persist_dir=d)
    assert c2.loaded == 2
    assert c2.get("fp-a") == {"verdict": 0, "outcome": "ok"}
    assert c2.get("fp-b")["verdict"] == 1
    c2.close()


def test_verdict_cache_torn_tail_keeps_valid_prefix(tmp_path):
    d = str(tmp_path / "verdicts")
    c = VerdictCache(capacity=16, persist_dir=d)
    c.put("fp-a", {"verdict": 0})
    c.put("fp-b", {"verdict": 1})
    c.close()
    seg = _segments(d)[-1]
    with open(seg, "r+b") as f:  # tear the final (fp-b) record
        f.truncate(os.path.getsize(seg) - 2)

    c2 = VerdictCache(capacity=16, persist_dir=d)
    assert c2.loaded == 1
    assert c2.get("fp-a") == {"verdict": 0}
    assert c2.get("fp-b") is None  # lost verdict = re-search, never wrong
    assert c2.recovery.torn_tail_bytes > 0
    c2.close()


def test_verdict_cache_foreign_records_skipped(tmp_path):
    d = str(tmp_path / "verdicts")
    log = SegmentLog(d)
    log.append(b"not json at all")
    log.append(json.dumps({"fp": "fp-x", "p": {"verdict": 2}}).encode())
    log.append(json.dumps({"wrong": "shape"}).encode())
    log.close()
    c = VerdictCache(capacity=16, persist_dir=d)
    assert c.loaded == 1 and c.get("fp-x") == {"verdict": 2}
    c.close()


# -- admission journal --------------------------------------------------------


def test_journal_orphans_and_compaction(tmp_path):
    d = str(tmp_path / "journal")
    j = JobJournal(d)
    j.accept(job=1, fingerprint="fp-1", client="a", priority=10, history="h1")
    j.accept(job=2, fingerprint="fp-2", client="b", priority=5, history="h2")
    j.accept(job=3, fingerprint="fp-3", client="c", priority=1, history="h3")
    j.done(job=1, fingerprint="fp-1", verdict=0, outcome="ok")
    j.reject(job=3)  # queue-full after the accept landed: record closed
    j.close()

    j2 = JobJournal(d)  # a new boot
    orphans = j2.orphans()
    assert [o["fp"] for o in orphans] == ["fp-2"]
    assert orphans[0]["history"] == "h2" and orphans[0]["client"] == "b"

    # re-accept under the new boot, then compact: prior boot disappears
    j2.accept(job=1, fingerprint="fp-2", client="b", priority=5, history="h2")
    j2.compact()
    j2.done(job=1, fingerprint="fp-2", verdict=0, outcome="ok")
    j2.close()
    assert JobJournal(d).orphans() == []


def test_journal_duplicate_fingerprints_collapse(tmp_path):
    j = JobJournal(str(tmp_path / "journal"))
    j.accept(job=1, fingerprint="fp-same", client="a", priority=10, history="h")
    j.accept(job=2, fingerprint="fp-same", client="a", priority=10, history="h")
    j.close()
    j2 = JobJournal(str(tmp_path / "journal"))
    assert len(j2.orphans()) == 1  # one re-run; the cache answers the twin
    j2.close()


# -- daemon-level restart behavior -------------------------------------------


def test_daemon_restart_answers_cached_without_checker(tmp_path):
    good = good_history()
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        first = client.submit(good, client="dur")
        assert first["verdict"] == 0 and first["cached"] is False

    cfg2 = _daemon_cfg(tmp_path, socket_path=str(tmp_path / "v2.sock"))
    with Verifyd(cfg2) as daemon2:
        assert daemon2.cache.loaded == 1
        client = VerifydClient(cfg2.socket_path, timeout=120)
        again = client.submit(good, client="dur")
        assert again["verdict"] == 0 and again["cached"] is True
        snap = client.stats()
        # the fingerprint was answered at admission: no job ever ran
        assert snap["completed"] == 0 and snap["cache_loaded"] == 1


def test_daemon_orphan_replay_after_unclean_stop(tmp_path):
    good = good_history()
    # Boot 1: workers=0 — the job is accepted (journaled) but never run;
    # exiting with it queued models a crash mid-job for the journal's
    # purposes (no done record lands).
    cfg = _daemon_cfg(tmp_path, workers=0)
    with Verifyd(cfg) as daemon:
        import socket as _socket

        with _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM) as s:
            s.connect(cfg.socket_path)
            s.sendall(
                encode_frame({"op": "submit", "history": good, "client": "w"})
            )
            deadline = time.monotonic() + 10
            while daemon.stats.snapshot()["admitted"] < 1:
                assert time.monotonic() < deadline, "job never admitted"
                time.sleep(0.01)

    # Boot 2: replay must re-run the orphan and cache its verdict.
    cfg2 = _daemon_cfg(tmp_path, socket_path=str(tmp_path / "v2.sock"))
    with Verifyd(cfg2) as daemon2:
        client = VerifydClient(cfg2.socket_path, timeout=120)
        deadline = time.monotonic() + 60
        while True:
            snap = client.stats()
            if snap["orphans_recovered"] >= 1 and snap["completed"] >= 1:
                break
            assert time.monotonic() < deadline, f"orphan never re-ran: {snap}"
            time.sleep(0.05)
        reply = client.submit(good, client="w2")
        assert reply["verdict"] == 0 and reply["cached"] is True
        # at-least-once promise kept and closed: the journal is clean now
        assert daemon2.journal.orphans() == []

    # Boot 3: nothing left to recover.
    cfg3 = _daemon_cfg(tmp_path, socket_path=str(tmp_path / "v3.sock"))
    with Verifyd(cfg3) as daemon3:
        assert daemon3.stats.snapshot()["orphans_recovered"] == 0


def test_daemon_orphan_with_invalid_history_is_reported(tmp_path):
    state = str(tmp_path / "state")
    j = JobJournal(os.path.join(state, "journal"))
    j.accept(job=1, fingerprint="fp-junk", client="x", priority=10, history="{broken\n")
    j.close()
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        snap = daemon.stats.snapshot()
        assert snap["orphans_recovered"] == 0  # reported, not resurrected
    with open(tmp_path / "stats.jsonl", encoding="utf-8") as f:
        events = [json.loads(line) for line in f if line.strip()]
    assert any(e["ev"] == "orphan_invalid" for e in events)


def test_fingerprint_of_history(tmp_path):
    """Regression guard: the durable cache keys on the same fingerprint
    across process lifetimes (no per-boot salt may sneak in)."""
    from s2_verification_tpu.checker.entries import prepare

    hist = prepare(list(ev.iter_history(good_history())), elide_trivial=True)
    assert history_fingerprint(hist) == history_fingerprint(hist)
