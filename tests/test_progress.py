"""Live search progress telemetry (ISSUE 18): sink cadence, EWMA/ETA
math, the supervised-child heartbeat seam, the ``watch`` op through
daemon and router, the distsearch stall clock, and per-lane batched
attribution.

Everything runs under the session-wide ``JAX_PLATFORMS=cpu`` pin.  The
governing invariants: heartbeats are time-gated (a trivial job emits
zero), folds are monotone in ``ops_committed``, and ``watch`` answers
are either definite rows or definite errors — never a hang.
"""

import io
import json
import os
import threading
import time

import pytest

from s2_verification_tpu.checker.batched import (
    BatchLane,
    check_batch_native,
    check_batch_vmap,
)
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.native import native_available
from s2_verification_tpu.checker.oracle import CheckOutcome, CheckResult
from s2_verification_tpu.checker.progress import ProgressSink
from s2_verification_tpu.models.encode import encode_batch
from s2_verification_tpu.service import scheduler as sched_mod
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.distsearch import (
    Coordinator,
    DistSearchConfig,
    _Attempt,
)
from s2_verification_tpu.service.progress import JobProgress
from s2_verification_tpu.service.protocol import ERR_DECODE, ERR_UNKNOWN_JOB
from s2_verification_tpu.service.router import (
    BackendSpec,
    RouterConfig,
    VerifydRouter,
)
from s2_verification_tpu.service.supervise import _progress_poll
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

needs_native = pytest.mark.skipif(
    not native_available(), reason="native C engine not built"
)


class Clock:
    """Injectable monotonic clock."""

    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def _history(i: int = 0) -> H:
    h = H()
    h.append_ok(1, [100 + i], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([100 + i]))
    h.append_ok(2, [200 + i, 300 + i], tail=3)
    h.read_ok(1, tail=3, stream_hash=fold([100 + i, 200 + i, 300 + i]))
    return h


def _text(i: int = 0) -> str:
    buf = io.StringIO()
    ev.write_history(_history(i).events, buf)
    return buf.getvalue()


def _daemon_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        no_viz=True,
        out_dir=str(tmp_path / "viz"),
        stats_log=None,
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _slow_engine(total: int = 30, step_s: float = 0.02):
    """A stand-in CPU engine that reports per-layer progress the way
    check_frontier does, slow enough for a watcher to sample it live."""

    def run(hist, budget, profile=False, progress=None):
        for i in range(1, total + 1):
            if progress is not None:
                progress.update(
                    ops_committed=i,
                    total_ops=total,
                    frontier_width=3 + (i % 5),
                    states_expanded=i * 7,
                    layer=i,
                    engine="frontier",
                    final=(i == total),
                )
            time.sleep(step_s)
        return CheckResult(CheckOutcome.OK), "frontier"

    return run


# -- sink cadence bounding ----------------------------------------------------


def test_sink_first_update_is_baseline_only():
    clock, out = Clock(), []
    sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    assert sink.update(ops_committed=0, total_ops=100) is False
    assert out == [] and sink.emitted == 0


def test_sink_cadence_is_time_gated_not_call_gated():
    clock, out = Clock(), []
    sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    # A hot layer loop: 100 offers over one second of wall clock must
    # leave at most two heartbeats (one per 0.5s interval).
    for i in range(100):
        sink.update(ops_committed=i, total_ops=100, layer=i)
        clock.tick(0.01)
    assert 1 <= len(out) <= 2
    assert all(rec["engine"] == "other" for rec in out)


def test_trivial_job_emits_zero_heartbeats():
    clock, out = Clock(), []
    sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    sink.update(ops_committed=0, total_ops=4)
    clock.tick(0.1)
    sink.update(ops_committed=2, total_ops=4)
    clock.tick(0.1)
    # The final offer lands inside the very first interval: silence.
    assert sink.update(ops_committed=4, total_ops=4, final=True) is False
    assert out == []


def test_sink_final_emits_once_past_one_interval():
    clock, out = Clock(), []
    sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    sink.update(ops_committed=0, total_ops=4)
    clock.tick(0.6)
    assert sink.update(ops_committed=4, total_ops=4, final=True) is True
    assert len(out) == 1 and out[0]["final"] is True


def test_sink_layer_rate_and_lane_attribution():
    clock, out = Clock(), []
    sink = ProgressSink(
        out.append, min_interval_s=0.5, time_fn=clock, engine="device", lane=3
    )
    sink.update(ops_committed=0, total_ops=10, layer=0)
    clock.tick(1.0)
    sink.update(ops_committed=5, total_ops=10, layer=5)
    assert len(out) == 1
    assert out[0]["layer_rate"] == pytest.approx(5.0)
    assert out[0]["engine"] == "device" and out[0]["lane"] == 3


def test_sink_multi_layer_launch_rate_attribution():
    """A speculative K-layer launch advances ``layer`` by K in one
    update: the rate must credit the full delta over the interval, not
    one-layer-per-heartbeat, and a layer-less offer in between must
    carry the baseline forward instead of resetting it to zero."""
    clock, out = Clock(), []
    sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    sink.update(ops_committed=0, total_ops=40, layer=0)  # baseline
    # One speculative dive covers layers 0 -> 4 in a single launch.
    clock.tick(2.0)
    sink.update(ops_committed=4, total_ops=40, layer=4)
    assert out[-1]["layer_rate"] == pytest.approx(2.0)  # 4 layers / 2 s

    # A layer-less fold (native child, service-side aggregation) between
    # layer-bearing updates: rate falls back to the ops delta...
    clock.tick(1.0)
    sink.update(ops_committed=6, total_ops=40)
    assert "layer" not in out[-1]
    assert out[-1]["layer_rate"] == pytest.approx(2.0)  # (6-4) ops / 1 s

    # ...and the NEXT layer-bearing update is measured against the
    # carried layer baseline (4), not a zero reset: 8-4 layers over 1 s,
    # not 8 layers over 1 s.
    clock.tick(1.0)
    sink.update(ops_committed=8, total_ops=40, layer=8)
    assert out[-1]["layer"] == 8
    assert out[-1]["layer_rate"] == pytest.approx(4.0)

    # Regression shape: a dive that finishes K layers inside one
    # interval then reports on the next boundary still averages to
    # K / elapsed, never 1 / elapsed.
    clock.tick(0.5)
    sink.update(ops_committed=20, total_ops=40, layer=20)
    assert out[-1]["layer_rate"] == pytest.approx((20 - 8) / 0.5)


# -- EWMA / ETA math with an injected clock -----------------------------------


def test_jobprogress_ewma_and_eta():
    clock = Clock()
    table = JobProgress(interval_s=0.5, ewma_alpha=0.3, time_fn=clock)
    sink = table.sink_for(7, fingerprint="fp7", shape="2x4x8")
    # Registered at job start: watch sees the row before any heartbeat.
    rows = table.rows()
    assert [r["job"] for r in rows] == [7]
    assert rows[0]["ops_committed"] == 0 and rows[0]["heartbeats"] == 0

    sink.update(ops_committed=0, total_ops=100)  # baseline
    clock.tick(1.0)
    sink.update(ops_committed=10, total_ops=100)
    row = table.get(7)
    assert row["ops_rate"] == pytest.approx(10.0)
    assert row["eta_s"] == pytest.approx(9.0)
    assert row["progress_ratio"] == pytest.approx(0.1)

    clock.tick(1.0)
    sink.update(ops_committed=20, total_ops=100)
    row = table.get(7)
    assert row["ops_rate"] == pytest.approx(10.0)
    assert row["eta_s"] == pytest.approx(8.0)

    # A stalled interval drags the EWMA down and pushes the ETA out.
    clock.tick(1.0)
    sink.update(ops_committed=20, total_ops=100)
    row = table.get(7)
    assert row["ops_rate"] == pytest.approx(7.0)
    assert row["eta_s"] == pytest.approx(80 / 7.0, rel=1e-3)

    # Monotone fold: a regressing sample can never move ops backwards.
    clock.tick(1.0)
    sink.update(ops_committed=5, total_ops=100)
    assert table.get(7)["ops_committed"] == 20

    table.finish(7, outcome="ok")
    assert table.rows() == []
    done = table.get(7)
    assert done["done"] is True and done["outcome"] == "ok"


def test_jobprogress_find_by_partition_prefix():
    table = JobProgress(interval_s=0.5, time_fn=Clock())
    table.sink_for(1, fingerprint="ppart:abcd1234abcd1234/p0")
    table.sink_for(2, fingerprint="ppart:abcd1234abcd1234/p1")
    table.sink_for(3, fingerprint="other")
    hits = table.find("ppart:abcd1234abcd1234/", prefix=True)
    assert [r["job"] for r in hits] == [1, 2]
    assert table.find("other") and not table.find("nope")


# -- supervised-child heartbeat round-trip ------------------------------------


def test_supervised_spool_roundtrip(tmp_path):
    path = str(tmp_path / "job1.progress.json")

    def spool(rec):  # the child side: atomic overwrite of the latest beat
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f)
        os.replace(tmp, path)

    clock, out = Clock(), []
    parent_sink = ProgressSink(out.append, min_interval_s=0.5, time_fn=clock)
    cancelled = []
    poll = _progress_poll(
        lambda: cancelled and cancelled[0] or None,
        parent_sink,
        path,
        min_interval_s=0.0,
    )

    spool({"ops_committed": 5, "total_ops": 10, "layer": 2, "engine": "device"})
    assert poll() is None  # baseline fold, no heartbeat yet
    clock.tick(1.0)
    spool({"ops_committed": 7, "total_ops": 10, "layer": 4, "engine": "device"})
    poll()
    assert len(out) == 1
    assert out[0]["ops_committed"] == 7 and out[0]["engine"] == "device"
    # Same stamp: deduped, the sink is not even offered.
    clock.tick(1.0)
    poll()
    assert len(out) == 1
    # The wrapper still carries the driver's cancel signal.
    cancelled.append("deadline")
    assert poll() == "deadline"


def test_supervised_spool_tolerates_garbage(tmp_path):
    path = str(tmp_path / "job2.progress.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write("not json{")
    sink = ProgressSink(lambda rec: None, time_fn=Clock())
    poll = _progress_poll(lambda: None, sink, path, min_interval_s=0.0)
    assert poll() is None  # malformed spool is ignored, never a crash


# -- watch op through the daemon ----------------------------------------------


def test_watch_live_job_monotone_then_done(tmp_path, monkeypatch):
    monkeypatch.setattr(sched_mod, "_cpu_check", _slow_engine())
    cfg = _daemon_cfg(tmp_path, progress_interval_s=0.05)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path)
        reply: dict = {}
        t = threading.Thread(
            target=lambda: reply.update(
                VerifydClient(cfg.socket_path).submit(_text(), timeout=60)
            ),
            daemon=True,
        )
        t.start()
        seen: list[dict] = []
        deadline = time.monotonic() + 30
        while t.is_alive() and time.monotonic() < deadline:
            for row in client.watch().get("progress") or []:
                seen.append(row)
            time.sleep(0.02)
        t.join(timeout=30)
        assert reply.get("verdict") == 0
        assert len(seen) >= 2
        ops = [r["ops_committed"] for r in seen]
        assert ops == sorted(ops) and ops[-1] > ops[0]
        assert all(r["engine"] in ("other", "frontier") for r in seen)
        # The finished job still answers by id, from the done ring.
        done = client.watch(job=seen[-1]["job"])["progress"][0]
        assert done["done"] is True


def test_watch_unknown_job_is_definite(tmp_path):
    cfg = _daemon_cfg(tmp_path)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path)
        with pytest.raises(VerifydError) as exc:
            client.watch(job=999999)
        assert exc.value.cls == ERR_UNKNOWN_JOB
        with pytest.raises(VerifydError) as exc:
            client.watch(fingerprint="no-such-fp")
        assert exc.value.cls == ERR_UNKNOWN_JOB
        # No selector: an empty board, not an error.
        assert client.watch()["progress"] == []


def test_watch_refused_when_heartbeats_disabled(tmp_path):
    cfg = _daemon_cfg(tmp_path, progress_interval_s=0.0)
    with Verifyd(cfg):
        with pytest.raises(VerifydError) as exc:
            VerifydClient(cfg.socket_path).watch()
        assert exc.value.cls == ERR_DECODE


# -- watch op through the router ----------------------------------------------


def _router_cfg(tmp_path, names, **overrides) -> RouterConfig:
    kw = dict(
        listen=str(tmp_path / "router.sock"),
        backends=tuple(
            BackendSpec(n, str(tmp_path / f"{n}.sock")) for n in names
        ),
        probe_interval_s=30.0,
    )
    kw.update(overrides)
    return RouterConfig(**kw)


def _backend_cfg(tmp_path, name, **overrides) -> VerifydConfig:
    return _daemon_cfg(
        tmp_path,
        socket_path=str(tmp_path / f"{name}.sock"),
        out_dir=str(tmp_path / f"viz-{name}"),
        **overrides,
    )


def test_watch_through_router_tags_nodes(tmp_path, monkeypatch):
    monkeypatch.setattr(sched_mod, "_cpu_check", _slow_engine())
    with Verifyd(_backend_cfg(tmp_path, "a", progress_interval_s=0.05)), \
            VerifydRouter(_router_cfg(tmp_path, ("a",))) as router:
        client = VerifydClient(router.cfg.listen)
        reply: dict = {}
        t = threading.Thread(
            target=lambda: reply.update(
                VerifydClient(router.cfg.listen).submit(_text(), timeout=60)
            ),
            daemon=True,
        )
        t.start()
        rows: list[dict] = []
        deadline = time.monotonic() + 30
        while t.is_alive() and time.monotonic() < deadline:
            rows.extend(client.watch().get("progress") or [])
            time.sleep(0.02)
        t.join(timeout=30)
        assert reply.get("verdict") == 0
        assert rows and all(r["node"] == "a" for r in rows)
        ops = [r["ops_committed"] for r in rows]
        assert ops == sorted(ops) and ops[-1] > ops[0]


def test_watch_through_router_unknown_job_is_definite(tmp_path):
    with Verifyd(_backend_cfg(tmp_path, "a")), VerifydRouter(
        _router_cfg(tmp_path, ("a",))
    ) as router:
        client = VerifydClient(router.cfg.listen)
        with pytest.raises(VerifydError) as exc:
            client.watch(job=424242)
        assert exc.value.cls == ERR_UNKNOWN_JOB
        assert client.watch()["progress"] == []


# -- distsearch: progress-rate stall clock vs wall clock ----------------------


class _WatchStub:
    """A backend client stub for the coordinator's progress poll."""

    def __init__(self):
        self.row = None  # None → answer UnknownJob (owner never reports)

    def watch(self, fingerprint=None, timeout=None):
        if self.row is None:
            raise VerifydError(ERR_UNKNOWN_JOB, "no such job")
        return {"progress": [dict(self.row)]}


def _coordinator(stub) -> Coordinator:
    return Coordinator(
        search="c" * 64,
        nodes=lambda: [("a", stub)],
        config=DistSearchConfig(progress_poll_s=0.5),
    )


def _poll_until_harvest(coord, attempt, now: float) -> float:
    """Launch one poll and harvest it; returns the harvest timestamp."""
    coord._poll_progress(attempt, now)
    assert attempt.poll_future is not None
    while not attempt.poll_future.done():
        time.sleep(0.005)
    now += 0.01
    coord._poll_progress(attempt, now)
    return now


def test_stall_clock_advances_only_with_progress():
    stub = _WatchStub()
    coord = _coordinator(stub)
    try:
        a = _Attempt(part="p0", epoch=1, node="a", future=None)
        granted_at = a.last_advance

        # Owner reports ops=5: the stall clock advances past grant time.
        stub.row = {"ops_committed": 5, "total_ops": 40, "states_expanded": 9}
        t1 = _poll_until_harvest(coord, a, now=granted_at + 10.0)
        assert a.ops == 5 and a.last_advance == t1 > granted_at
        assert coord.progress["p0"]["ops_committed"] == 5
        assert coord.progress["p0"]["node"] == "a"

        # Same numbers again: the search stopped moving — the clock does
        # not advance, so the straggler budget now runs against it.
        t2 = _poll_until_harvest(coord, a, now=t1 + 1.0)
        assert a.last_advance == t1 < t2
        assert coord.progress["p0"]["stalled_s"] > 0

        # It moves again: fresh clock.
        stub.row = {"ops_committed": 11, "total_ops": 40, "states_expanded": 20}
        t3 = _poll_until_harvest(coord, a, now=t2 + 1.0)
        assert a.ops == 11 and a.last_advance == t3

        # a saw progress, so an eventual steal is a "stall-steal".
        assert a.ops >= 0
        snap = coord.progress_snapshot()
        assert snap["partitions"]["p0"]["ops_committed"] == 11
    finally:
        coord._pool.shutdown(wait=False)


def test_silent_owner_degrades_to_wall_clock_rule():
    stub = _WatchStub()  # row stays None: every watch answers UnknownJob
    coord = _coordinator(stub)
    try:
        a = _Attempt(part="p0", epoch=1, node="a", future=None)
        granted_at = a.last_advance
        t1 = _poll_until_harvest(coord, a, now=granted_at + 10.0)
        _poll_until_harvest(coord, a, now=t1 + 1.0)
        # No heartbeat ever seen: the stall clock never moved off grant
        # time (legacy wall-clock stealing) and the steal reason stays
        # the legacy "steal", not "stall-steal".
        assert a.last_advance == granted_at
        assert a.ops == -1 and a.expanded == -1
        assert "p0" not in coord.progress
    finally:
        coord._pool.shutdown(wait=False)


def test_stall_steal_reason_is_counted():
    counts: dict[str, int] = {}
    stub = _WatchStub()

    class _Seg:
        key = "seg0"

    class _GrantStub:
        def grant(self, **kw):
            return {"ok": True}

        def delta(self, *a, **kw):
            return {"verdict": 2}

    coord = Coordinator(
        search="c" * 64,
        nodes=lambda: [("a", stub)],
        config=DistSearchConfig(progress_poll_s=0.5),
        counter=lambda key, n=1: counts.__setitem__(
            key, counts.get(key, 0) + n
        ),
    )
    try:
        coord._grant_and_ship(
            _Seg(), "", "p0", (), "a", _GrantStub(), "stall-steal"
        )
        assert coord.stall_steals == 1 and coord.steals == 1
        assert counts.get("stall_stolen") == 1 and counts.get("stolen") == 1
        coord._grant_and_ship(
            _Seg(), "", "p1", (), "a", _GrantStub(), "steal"
        )
        assert coord.stall_steals == 1 and coord.steals == 2
        assert counts.get("stall_stolen") == 1 and counts.get("stolen") == 2
    finally:
        coord._pool.shutdown(wait=False)


# -- batched lanes: per-lane attribution --------------------------------------


def _busy_history(i: int) -> H:
    """Three overlapping indefinite appends: their order is ambiguous and
    each forks committed/uncommitted, so the lane carries real search
    work (a serial history elides to a trivially-OK lane that —
    correctly — never heartbeats)."""
    from s2_verification_tpu.utils.events import AppendIndefiniteFailure

    h = H()
    calls = [h.call_append(k + 1, [100 * (k + 1) + i]) for k in range(3)]
    for k, op in enumerate(calls):
        h.finish(k + 1, op, AppendIndefiniteFailure())
    h.read_ok(4, tail=0, stream_hash=fold([]))
    return h


def _lanes(n: int):
    hists = [
        prepare(_busy_history(i).events, elide_trivial=True) for i in range(n)
    ]
    return [
        BatchLane(h, enc) for h, enc in zip(hists, encode_batch(list(hists)))
    ]


def test_batch_vmap_per_lane_attribution():
    lanes = _lanes(3)
    outs: list[list[dict]] = [[] for _ in lanes]
    sinks = [
        ProgressSink(outs[i].append, min_interval_s=0.0, engine="batch-vmap")
        for i in range(len(lanes))
    ]
    verdicts = check_batch_vmap(lanes, progress=sinks)
    for i, (lane, v) in enumerate(zip(lanes, verdicts)):
        if v.result is None:
            continue  # escalated lanes report nothing final
        assert outs[i], f"lane {i} never heartbeat"
        last = outs[i][-1]
        # Each lane's heartbeats carry its OWN op counts — attribution
        # never bleeds across launch-mates.
        assert last["total_ops"] == len(lane.history.ops)
        assert last["engine"] == "batch-vmap"
        if v.result.outcome == CheckOutcome.OK:
            assert last["ops_committed"] == len(lane.history.ops)


@needs_native
def test_batch_native_per_lane_attribution():
    lanes = _lanes(3)
    outs: list[list[dict]] = [[] for _ in lanes]
    sinks = [
        ProgressSink(outs[i].append, min_interval_s=0.0) for i in range(3)
    ]
    verdicts = check_batch_native(lanes, progress=sinks)
    assert all(v.result is not None for v in verdicts)
    for i, lane in enumerate(lanes):
        assert outs[i]
        assert outs[i][-1]["total_ops"] == len(lane.history.ops)
        assert outs[i][-1]["engine"] == "batch-native"


def test_batch_skipped_lane_stays_silent():
    lanes = _lanes(2)
    outs: list[list[dict]] = [[] for _ in lanes]
    sinks = [
        ProgressSink(outs[i].append, min_interval_s=0.0) for i in range(2)
    ]
    verdicts = check_batch_vmap(
        lanes, skip=lambda i: "cancelled" if i == 0 else None, progress=sinks
    )
    assert verdicts[0].skipped == "cancelled"
    assert outs[0] == []  # a skipped lane must not heartbeat
