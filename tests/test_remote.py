"""Authenticated TCP transport + frame bounds + client retry policy.

The unix-socket transport's behavior is pinned by test_service.py; this
file covers what the remote transport adds: HMAC frame auth (rejected
before admission), the per-frame size bound (a definite protocol error,
not an unbounded read), and the client's transient/permanent failure
split (exit 69 "nothing answered" vs 76 "reached but refused").
"""

import io
import json
import random
import socket as _socket

import pytest

from s2_verification_tpu.cli import main as cli_main
from s2_verification_tpu.service.client import (
    VerifydBusy,
    VerifydClient,
    VerifydRefused,
    VerifydUnavailable,
)
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.protocol import (
    decode_frame,
    encode_frame,
    parse_hostport,
    sign_frame,
    verify_frame,
)
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

SECRET = b"test-shared-secret"


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    return _text(h)


def bad_history() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=12345)
    return _text(h)


def _tcp_cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        no_viz=True,
        out_dir=str(tmp_path / "viz"),
        tcp="127.0.0.1:0",
        secret=SECRET,
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


# -- protocol units -----------------------------------------------------------


def test_sign_verify_round_trip_and_tamper():
    frame = {"op": "submit", "history": "x", "client": "c"}
    signed = sign_frame(frame, SECRET)
    assert verify_frame(signed, SECRET)
    assert not verify_frame(signed, b"other-secret")
    tampered = dict(signed, history="y")
    assert not verify_frame(tampered, SECRET)
    assert not verify_frame(frame, SECRET)  # unsigned


def test_sign_is_order_independent():
    a = sign_frame({"op": "ping", "z": 1, "a": 2}, SECRET)
    b = sign_frame({"a": 2, "z": 1, "op": "ping"}, SECRET)
    assert a["auth"] == b["auth"]


def test_parse_hostport():
    assert parse_hostport("127.0.0.1:7070") == ("127.0.0.1", 7070)
    assert parse_hostport(":7070") == ("0.0.0.0", 7070)
    with pytest.raises(ValueError):
        parse_hostport("no-port")
    with pytest.raises(ValueError):
        parse_hostport("host:notanumber")


def test_tcp_listener_requires_secret(tmp_path):
    with pytest.raises(ValueError, match="secret"):
        Verifyd(_tcp_cfg(tmp_path, secret=None))


def test_client_tcp_address_requires_secret():
    with pytest.raises(ValueError, match="secret"):
        VerifydClient("127.0.0.1:7070")


# -- TCP round trip -----------------------------------------------------------


def test_tcp_round_trip_parity_with_unix(tmp_path):
    cfg = _tcp_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        assert daemon.tcp_port  # ephemeral port was bound and published
        tcp = VerifydClient(
            f"127.0.0.1:{daemon.tcp_port}", timeout=120, secret=SECRET
        )
        unix = VerifydClient(cfg.socket_path, timeout=120)

        assert tcp.ping()["server"] == "verifyd"
        # same verdicts through both transports; the unix path is
        # untouched by the TCP feature (no auth field needed)
        assert tcp.submit(good_history(), client="t")["verdict"] == 0
        assert tcp.submit(bad_history(), client="t")["verdict"] == 1
        reply = unix.submit(good_history(), client="u")
        assert reply["verdict"] == 0 and reply["cached"] is True


def test_wrong_secret_rejected_before_admission(tmp_path):
    cfg = _tcp_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        before = daemon.stats.snapshot()["submitted"]
        bad = VerifydClient(
            f"127.0.0.1:{daemon.tcp_port}", timeout=10, secret=b"wrong"
        )
        with pytest.raises(VerifydRefused) as ei:
            bad.submit(good_history(), client="intruder")
        assert ei.value.cls == "AuthError"
        assert ei.value.transient is False  # retrying cannot fix a bad secret
        snap = daemon.stats.snapshot()
        assert snap["submitted"] == before  # nothing reached admission
        assert snap["auth_rejects"] >= 1


def test_unsigned_frame_rejected(tmp_path):
    cfg = _tcp_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        with _socket.create_connection(
            ("127.0.0.1", daemon.tcp_port), timeout=10
        ) as s:
            s.sendall(encode_frame({"op": "ping"}))
            resp = decode_frame(s.makefile("rb").readline())
        assert resp["err"]["class"] == "AuthError"


def test_tcp_replies_are_signed(tmp_path):
    cfg = _tcp_cfg(tmp_path)
    with Verifyd(cfg) as daemon:
        with _socket.create_connection(
            ("127.0.0.1", daemon.tcp_port), timeout=10
        ) as s:
            s.sendall(encode_frame(sign_frame({"op": "ping"}, SECRET)))
            resp = decode_frame(s.makefile("rb").readline())
        assert verify_frame(resp, SECRET)


# -- frame bounds (satellite: protocol.py size bound on read) -----------------


def test_oversized_frame_gets_definite_protocol_error(tmp_path):
    cfg = _tcp_cfg(tmp_path, tcp=None, secret=None, frame_max_bytes=4096)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=10)
        with pytest.raises(VerifydRefused) as ei:
            client.submit("x" * 8192, client="big")
        assert ei.value.cls == "FrameTooLarge"


def test_large_history_within_bound_is_accepted(tmp_path):
    # Regression: the old implicit bound was asyncio's 64 KiB stream
    # default, which rejected legal large histories outright.
    h = H()
    hashes = [10**15 + i for i in range(5000)]  # one fat append line
    h.append_ok(1, hashes, tail=5000)
    h.read_ok(2, tail=5000, stream_hash=fold(hashes))
    text = _text(h)
    assert len(text.encode()) > 64 << 10
    cfg = _tcp_cfg(tmp_path, tcp=None, secret=None)
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path, timeout=120)
        assert client.submit(text, client="fat")["verdict"] == 0


def test_malformed_frame_is_frame_error_not_decode_error(tmp_path):
    # FrameError (transport noise, retryable) vs DecodeError (bad
    # history, the client's bug): distinct classes, distinct handling.
    cfg = _tcp_cfg(tmp_path, tcp=None, secret=None)
    with Verifyd(cfg):
        with _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM) as s:
            s.connect(cfg.socket_path)
            s.sendall(b"\xff not json\n")
            resp = decode_frame(s.makefile("rb").readline())
        assert resp["err"]["class"] == "FrameError"


# -- client retry policy ------------------------------------------------------


def test_unavailable_after_retries(tmp_path, monkeypatch):
    sleeps: list[float] = []
    monkeypatch.setattr("time.sleep", sleeps.append)
    client = VerifydClient(str(tmp_path / "nothing.sock"), timeout=1)
    with pytest.raises(VerifydUnavailable):
        client.submit_with_retry(
            "x", retries=3, backoff_s=0.5, rng=random.Random(0)
        )
    # exponential envelope with jitter: attempt n sleeps in [0, 0.5 * 2^n]
    assert len(sleeps) == 3
    for n, s in enumerate(sleeps):
        assert 0 <= s <= 0.5 * (2**n)


def test_auth_refusal_is_not_retried(tmp_path, monkeypatch):
    cfg = _tcp_cfg(tmp_path)
    sleeps: list[float] = []
    monkeypatch.setattr("time.sleep", sleeps.append)
    with Verifyd(cfg) as daemon:
        bad = VerifydClient(
            f"127.0.0.1:{daemon.tcp_port}", timeout=10, secret=b"wrong"
        )
        with pytest.raises(VerifydRefused):
            bad.submit_with_retry(good_history(), retries=5, backoff_s=0.01)
        assert sleeps == []  # definite refusal: zero retry sleeps
        assert daemon.stats.snapshot()["auth_rejects"] == 1


def test_busy_retry_honors_daemon_hint(tmp_path, monkeypatch):
    # workers=0 + depth=1: the first job parks, the second is rejected
    # with the daemon's retry-after hint, which the client must sleep.
    cfg = _tcp_cfg(
        tmp_path, tcp=None, secret=None, workers=0, queue_depth=1
    )
    sleeps: list[float] = []
    monkeypatch.setattr("time.sleep", sleeps.append)
    with Verifyd(cfg) as daemon:
        with _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM) as parked:
            parked.connect(cfg.socket_path)
            parked.sendall(
                encode_frame(
                    {"op": "submit", "history": good_history(), "client": "hog"}
                )
            )
            import time as _time

            deadline = _time.monotonic() + 10
            while len(daemon.queue) < 1:  # busy-wait: sleep is patched
                assert _time.monotonic() < deadline, "first job never admitted"
            client = VerifydClient(cfg.socket_path, timeout=10)
            with pytest.raises(VerifydBusy):
                client.submit_with_retry(bad_history(), retries=2)
        hint = daemon.stats.retry_after_hint(1)
        assert sleeps and all(s == hint for s in sleeps)


# -- CLI exit codes -----------------------------------------------------------


def test_cli_submit_tcp_round_trip_and_exit_76(tmp_path):
    cfg = _tcp_cfg(tmp_path)
    good = tmp_path / "good.jsonl"
    good.write_text(good_history(), encoding="utf-8")
    right = tmp_path / "secret.txt"
    right.write_text(SECRET.decode() + "\n", encoding="utf-8")
    wrong = tmp_path / "wrong.txt"
    wrong.write_text("not-the-secret\n", encoding="utf-8")
    with Verifyd(cfg) as daemon:
        addr = f"127.0.0.1:{daemon.tcp_port}"
        assert (
            cli_main(
                ["submit", "-file", str(good), "-socket", addr,
                 "--secret-file", str(right)]
            )
            == 0
        )
        # reached the daemon, refused: 76 (EX_PROTOCOL), not 69
        assert (
            cli_main(
                ["submit", "-file", str(good), "-socket", addr,
                 "--secret-file", str(wrong)]
            )
            == 76
        )


def test_cli_submit_tcp_without_secret_is_usage_error(tmp_path):
    good = tmp_path / "good.jsonl"
    good.write_text(good_history(), encoding="utf-8")
    assert (
        cli_main(["submit", "-file", str(good), "-socket", "127.0.0.1:1"]) == 64
    )
