"""AlertEngine: rule grammar, delivery, backoff, dedup, edge triggering.

All delivery tests run against a real stdlib HTTP receiver on an
ephemeral loopback port whose responses are scripted per attempt, so the
retry/backoff path exercises actual sockets; clocks and sleeps are
injected so no test waits on real backoff.
"""

import http.server
import json
import threading

import pytest

from s2_verification_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    builtin_rules,
    parse_rule,
)
from s2_verification_tpu.obs.metrics import MetricsRegistry


class _Receiver:
    """Scripted webhook endpoint: ``script`` is the status code per
    attempt (exhausted → 200).  Bodies of accepted (2xx) posts are kept."""

    def __init__(self, script=()):
        self.bodies = []
        self.attempts = 0
        script = list(script)
        recv = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 - stdlib handler name
                recv.attempts += 1
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                code = script.pop(0) if script else 200
                if 200 <= code < 300:
                    recv.bodies.append(json.loads(body.decode("utf-8")))
                self.send_response(code)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}/alert"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()


class _Recorder:
    """FlightRecorder stand-in capturing alert records and dump markers."""

    def __init__(self):
        self.alerts = []
        self.dumps = []

    def record_alert(self, alert):
        self.alerts.append(dict(alert))

    def dump(self, reason, **fields):
        self.dumps.append({"reason": reason, **fields})


def _engine(url, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("sleep_fn", lambda s: None)
    return AlertEngine(url, **kw)


# -- rule grammar -----------------------------------------------------------


def test_parse_rule_event():
    r = parse_rule("slo_breach")
    assert r.kind == "event" and r.event == "slo_breach"
    assert r.severity == "page"


def test_parse_rule_field_threshold():
    r = parse_rule("done.wall_s>30")
    assert r == AlertRule(
        name="done.wall_s>30", kind="field", event="done", field="wall_s",
        op=">", threshold=30.0, severity="warn",
    )


def test_parse_rule_metric_threshold_longest_op_wins():
    r = parse_rule("metric:verifyd_job_errors_total>=5")
    assert r.kind == "metric"
    assert r.metric == "verifyd_job_errors_total"
    assert r.op == ">=" and r.threshold == 5.0


@pytest.mark.parametrize(
    "spec",
    ["", "  ", "done.>3", ".wall_s>3", "wall_s>", "metric:>5",
     "metric:foo>bar", "no spaces allowed", "a.b.c>x"],
)
def test_parse_rule_rejects_nonsense(spec):
    with pytest.raises(ValueError):
        parse_rule(spec)


def test_builtin_rules_page_on_breach_and_regression():
    names = {r.name for r in builtin_rules()}
    assert names == {
        "slo_breach",
        "perf_regression",
        "retrace_storm",
        "job_quarantined",
        "writer_degraded",
        "checker_false_verdict",
    }
    assert all(r.severity == "page" for r in builtin_rules())


# -- delivery ---------------------------------------------------------------


def test_delivers_alertmanager_payload():
    recv = _Receiver()
    recorder = _Recorder()
    eng = _engine(recv.url, recorder=recorder)
    try:
        eng.observe_event(
            {"ev": "slo_breach", "t": 123.0, "reason": "burn", "shape": "4x2x8"}
        )
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 1
        payload = recv.bodies[0]
        assert isinstance(payload, list) and len(payload) == 1
        alert = payload[0]
        assert alert["labels"]["alertname"] == "slo_breach"
        assert alert["labels"]["service"] == "verifyd"
        assert alert["labels"]["severity"] == "page"
        assert alert["labels"]["shape"] == "4x2x8"
        assert "T" in alert["startsAt"] and alert["startsAt"].endswith("Z")
        assert "slo_breach" in alert["annotations"]["summary"]
        detail = json.loads(alert["annotations"]["detail"])
        assert detail["reason"] == "burn"
        # flight ring got the alert record on the firing path
        assert recorder.alerts == [
            {"rule": "slo_breach", "event": "slo_breach", "severity": "page"}
        ]
        sent = eng.registry.get("verifyd_alerts_sent_total")
        assert sum(sent.snapshot().values()) == 1
    finally:
        eng.close()
        recv.close()


def test_5xx_backs_off_then_succeeds():
    recv = _Receiver(script=[503, 500])
    sleeps = []
    eng = _engine(recv.url, backoff_s=0.5, sleep_fn=sleeps.append)
    try:
        eng.observe_event({"ev": "slo_breach"})
        assert eng.flush(timeout=10.0)
        assert recv.attempts == 3  # 503, 500, 200
        assert len(recv.bodies) == 1
        # full jitter: each sleep within the exponential cap for its attempt
        assert len(sleeps) == 2
        assert 0.0 <= sleeps[0] <= 0.5
        assert 0.0 <= sleeps[1] <= 1.0
        sent = eng.registry.get("verifyd_alerts_sent_total")
        failed = eng.registry.get("verifyd_alerts_failed_total")
        assert sum(sent.snapshot().values()) == 1
        assert sum(failed.snapshot().values()) == 0
    finally:
        eng.close()
        recv.close()


def test_permanent_failure_counts_and_dumps():
    recv = _Receiver(script=[500, 500, 500])
    recorder = _Recorder()
    eng = _engine(recv.url, retries=2, recorder=recorder)
    try:
        eng.observe_event({"ev": "slo_breach"})
        assert eng.flush(timeout=10.0)
        assert recv.attempts == 3  # initial + 2 retries, all 500
        assert recv.bodies == []
        failed = eng.registry.get("verifyd_alerts_failed_total")
        assert failed.value(rule="slo_breach") == 1
        assert len(recorder.dumps) == 1
        dump = recorder.dumps[0]
        assert dump["reason"] == "alert_failed"
        assert dump["rule"] == "slo_breach"
        assert dump["attempts"] == 3
        assert "500" in dump["error"]
    finally:
        eng.close()
        recv.close()


def test_definite_4xx_is_not_retried():
    recv = _Receiver(script=[400, 200, 200])
    eng = _engine(recv.url, retries=3)
    try:
        eng.observe_event({"ev": "slo_breach"})
        assert eng.flush(timeout=10.0)
        assert recv.attempts == 1  # 400 is definite: no retry
        failed = eng.registry.get("verifyd_alerts_failed_total")
        assert sum(failed.snapshot().values()) == 1
    finally:
        eng.close()
        recv.close()


# -- dedup / re-arm ---------------------------------------------------------


def test_dedup_window_suppresses_then_rearms():
    recv = _Receiver()
    clock = [1000.0]
    eng = _engine(recv.url, dedup_s=300.0, time_fn=lambda: clock[0])
    try:
        eng.observe_event({"ev": "slo_breach"})
        clock[0] += 10.0
        eng.observe_event({"ev": "slo_breach"})  # inside the window
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 1
        snap = eng.snapshot()
        assert snap["rules"]["slo_breach"]["fired"] == 1
        assert snap["rules"]["slo_breach"]["suppressed"] == 1
        sup = eng.registry.get("verifyd_alerts_suppressed_total")
        assert sup.value(rule="slo_breach") == 1

        clock[0] += 300.0  # window over: delivery resumes
        eng.observe_event({"ev": "slo_breach"})
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 2
    finally:
        eng.close()
        recv.close()


def test_field_rule_edge_triggered_rearm():
    recv = _Receiver()
    clock = [0.0]
    eng = _engine(
        recv.url,
        rules=[parse_rule("done.wall_s>1")],
        dedup_s=0.0,
        time_fn=lambda: clock[0],
    )
    try:
        for wall in (2.0, 3.0, 5.0):  # one crossing, held above
            clock[0] += 1.0
            eng.observe_event({"ev": "done", "wall_s": wall})
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 1  # fired on the edge only

        clock[0] += 1.0
        eng.observe_event({"ev": "done", "wall_s": 0.5})  # back in band
        clock[0] += 1.0
        eng.observe_event({"ev": "done", "wall_s": 2.0})  # second crossing
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 2
    finally:
        eng.close()
        recv.close()


def test_metric_rule_thresholds_registry_value():
    recv = _Receiver()
    registry = MetricsRegistry()
    errors = registry.counter(
        "job_errors_total", "test counter", labelnames=("kind",)
    )
    eng = _engine(
        recv.url,
        rules=[parse_rule("metric:job_errors_total>=3")],
        registry=registry,
        dedup_s=0.0,
    )
    try:
        errors.inc(kind="a")
        eng.observe_event({"ev": "done"})  # 1 < 3: quiet
        errors.inc(kind="a")
        errors.inc(kind="b")  # labeled sum = 3
        eng.observe_event({"ev": "done"})
        eng.observe_event({"ev": "done"})  # still over: edge-triggered, quiet
        assert eng.flush(timeout=10.0)
        assert len(recv.bodies) == 1
        assert recv.bodies[0][0]["labels"]["severity"] == "warn"
    finally:
        eng.close()
        recv.close()


def test_unmatched_events_deliver_nothing():
    recv = _Receiver()
    eng = _engine(recv.url)
    try:
        eng.observe_event({"ev": "done", "wall_s": 0.1})
        eng.observe_event({"ev": "accept"})
        eng.observe_event({"no_event_key": True})
        assert eng.flush(timeout=5.0)
        assert recv.bodies == [] and recv.attempts == 0
    finally:
        eng.close()
        recv.close()
