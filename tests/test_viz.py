"""The failure artifact's explorable partial-linearization view.

Reference analog: ``porcupine.Visualize`` renders per-op partial
linearizations a reader can explore per client on a failed check
(golang/s2-porcupine/main.go:606-631).  The artifact here must carry, for
each deepest configuration: one concrete linearization order (ordinals),
the refusing ops, and a per-client breakdown naming the culprit.
"""

from __future__ import annotations

import json
import re

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.diagnostics import deepest_refusals, derive_path
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.collector.collect import CollectConfig, collect_history
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from s2_verification_tpu.utils.events import LabeledEvent, ReadSuccess
from s2_verification_tpu.viz import render_html


def _tampered_history():
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=3,
            num_ops_per_client=15,
            workflow="regular",
            seed=3,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.2, max_latency=0.001),
        )
    )
    out, done = [], False
    for e in events:
        if not done and isinstance(e.event, ReadSuccess) and e.event.tail > 0:
            e = LabeledEvent(
                ReadSuccess(
                    tail=e.event.tail, stream_hash=e.event.stream_hash ^ 1
                ),
                e.client_id,
                e.op_id,
            )
            done = True
        out.append(e)
    assert done
    return prepare(out)


def _cfg_payload(html_text: str):
    m = re.search(
        r'<script type="application/json" id="cfg-data">(.*?)</script>',
        html_text,
        re.S,
    )
    assert m, "failure artifact is missing the cfg-data payload"
    return json.loads(m.group(1).replace("<\\/", "</"))


def test_failure_artifact_has_explorable_configurations():
    hist = _tampered_history()
    res = check(hist, time_budget_s=120.0)
    assert res.outcome == CheckOutcome.ILLEGAL
    # The oracle doesn't fill refusals itself; the CLI re-derives them
    # (cli.py) — mirror that here.
    res.refusals = [deepest_refusals(hist, res.deepest or [])]
    html_text = render_html(hist, res)

    cfgs = _cfg_payload(html_text)
    assert len(cfgs) == len(res.refusals)
    cfg0 = cfgs[0]
    # One concrete order over the deepest prefix: ordinals 1..n, one per
    # linearized op.
    n_prefix = len(res.refusals[0][0])
    assert len(cfg0["ord"]) == n_prefix
    assert sorted(cfg0["ord"].values()) == list(range(1, n_prefix + 1))
    # The refusing culprit is named, and attributed to its client.
    assert cfg0["refused"]
    assert any("REFUSES op" in txt for txt in cfg0["clients"].values())
    # The timeline carries the hooks the selector re-annotates through.
    assert 'data-opid=' in html_text and 'class="client-summary"' in html_text


def test_derive_path_orders_a_device_style_prefix_set():
    """Device configs hand viz a SORTED prefix set; derive_path must
    recover a valid order for it (or the artifact loses its ordinals)."""
    hist = _tampered_history()
    res = check(hist, time_budget_s=120.0)
    prefix = sorted(res.deepest)
    order, state = derive_path(hist, prefix)
    assert order is not None and state is not None
    assert sorted(order) == prefix


def test_script_selectors_match_rendered_markup():
    """No JS engine exists in this environment to execute the artifact's
    selector script, so pin the contract statically: every id/class/data
    attribute the script queries must exist in the rendered failure HTML
    (and the payload fields it reads must match what render_html emits) —
    the drift that would actually break the explorable view."""
    hist = _tampered_history()
    res = check(hist, time_budget_s=120.0)
    res.refusals = [deepest_refusals(hist, res.deepest or [])]
    html_text = render_html(hist, res)

    # Selectors the script queries.
    for needle in (
        "getElementById('cfg-data')",
        ".op[data-opid]",
        "'.client-summary'",
        "dataset.basetip",
        "dataset.client",
    ):
        assert needle in html_text, needle
    # ...and their rendered counterparts.
    for markup in (
        'id="cfg-data"',
        "data-opid=",
        "data-basetip=",
        'class="client-summary" data-client=',
    ):
        assert markup in html_text, markup
    # Payload fields the script reads per configuration.
    cfg0 = _cfg_payload(html_text)[0]
    assert set(cfg0) >= {"ord", "refused", "clients", "label"}


def test_ok_artifact_has_no_config_payload():
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=2,
            num_ops_per_client=10,
            workflow="regular",
            seed=4,
            indefinite_failure_backoff_s=0.0,
        )
    )
    hist = prepare(events)
    res = check(hist, time_budget_s=60.0)
    assert res.outcome == CheckOutcome.OK
    html_text = render_html(hist, res)
    assert 'id="cfg-data"' not in html_text
    # OK ordinals stay server-rendered.
    assert '<span class="ord">' in html_text
