"""Bit-exactness of the device u64 math and XXH3 kernel vs the host library."""

import random
import struct

import jax
import jax.numpy as jnp
import numpy as np
import xxhash

from s2_verification_tpu.ops import u64
from s2_verification_tpu.ops.xxh3 import (
    chain_hash,
    fold_record_hashes_masked,
    xxh3_8byte_seeded,
)
from s2_verification_tpu.utils import hashing

M = (1 << 64) - 1
rng = random.Random(0xABCD)


def u(vals):
    vals = np.asarray(vals, dtype=np.uint64)
    return u64.U64(
        jnp.asarray((vals >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((vals & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
    )


def ints(x):
    return u64.to_ints(x)


def rand64(n):
    return [rng.getrandbits(64) for _ in range(n)]


def test_u64_arith_matches_python():
    a = rand64(500)
    b = rand64(500)
    ua, ub = u(a), u(b)
    np.testing.assert_array_equal(ints(u64.add(ua, ub)), [(x + y) & M for x, y in zip(a, b)])
    np.testing.assert_array_equal(ints(u64.sub(ua, ub)), [(x - y) & M for x, y in zip(a, b)])
    np.testing.assert_array_equal(ints(u64.mul(ua, ub)), [(x * y) & M for x, y in zip(a, b)])
    np.testing.assert_array_equal(ints(u64.xor(ua, ub)), [x ^ y for x, y in zip(a, b)])


def test_u64_shifts_and_rotations():
    a = rand64(64)
    ua = u(a)
    for k in [0, 1, 7, 28, 31, 32, 33, 35, 49, 63]:
        np.testing.assert_array_equal(ints(u64.shl(ua, k)), [(x << k) & M for x in a])
        np.testing.assert_array_equal(ints(u64.shr(ua, k)), [x >> k for x in a])
        np.testing.assert_array_equal(
            ints(u64.rotl(ua, k)), [((x << k) | (x >> (64 - k))) & M if k else x for x in a]
        )


def test_u64_edge_values():
    edge = [0, 1, M, M - 1, 1 << 32, (1 << 32) - 1, (1 << 63), 0xFFFFFFFF00000000]
    pairs = [(x, y) for x in edge for y in edge]
    ua = u([p[0] for p in pairs])
    ub = u([p[1] for p in pairs])
    np.testing.assert_array_equal(ints(u64.add(ua, ub)), [(x + y) & M for x, y in pairs])
    np.testing.assert_array_equal(ints(u64.mul(ua, ub)), [(x * y) & M for x, y in pairs])
    np.testing.assert_array_equal(ints(u64.sub(ua, ub)), [(x - y) & M for x, y in pairs])


def test_xxh3_bit_exact_vs_host_library():
    vals = rand64(2000)
    seeds = [rng.getrandbits(64) if i % 2 else rng.getrandbits(32) for i in range(2000)]
    got = ints(jax.jit(xxh3_8byte_seeded)(u(vals), u(seeds)))
    want = [
        xxhash.xxh3_64_intdigest(struct.pack("<Q", v), seed=s)
        for v, s in zip(vals, seeds)
    ]
    np.testing.assert_array_equal(got, want)


def test_chain_hash_pinned_vectors():
    foo = hashing.record_hash(b"foo")
    h1 = ints(chain_hash(u([0]), u([foo])))[0]
    h2 = ints(chain_hash(u([h1]), u([hashing.record_hash(b"bar")])))[0]
    h3 = ints(chain_hash(u([h2]), u([hashing.record_hash(b"baz")])))[0]
    assert h1 == 0x4D2B003EE417C3A5
    assert h2 == 0x132E5D5DD7936EDD
    assert h3 == 0x732EE99ABC5002FF


def scalar(value):
    arr = u([value])
    return u64.U64(arr.hi[0], arr.lo[0])


def test_fold_masked_matches_host():
    for trial in range(20):
        n = rng.randint(1, 30)
        pad = rng.randint(0, 10)
        hs = rand64(n)
        start = rng.getrandbits(64)
        mask = np.array([True] * n + [False] * pad)
        padded = u(hs + [0] * pad)
        got = ints(jax.jit(fold_record_hashes_masked)(scalar(start), padded, mask))
        want = hashing.fold_record_hashes(start, hs)
        assert int(got) == want, f"trial {trial}"


def test_fold_empty_mask_is_identity():
    padded = u(rand64(8))
    got = ints(fold_record_hashes_masked(scalar(77), padded, np.zeros(8, bool)))
    assert int(got) == 77


def test_fold_unroll_factors_agree():
    """The accelerator unroll (ops/xxh3.py _fold_unroll) must be a pure
    latency trade: every factor computes the identical fold, including
    lengths the factor does not divide."""
    import s2_verification_tpu.ops.xxh3 as xxh3_mod

    for n, pad in ((1, 0), (5, 3), (13, 3), (16, 0), (30, 2)):
        hs = rand64(n)
        start = rng.getrandbits(64)
        mask = np.array([True] * n + [False] * pad)
        padded = u(hs + [0] * pad)
        want = hashing.fold_record_hashes(start, hs)
        for factor in (1, 2, 8):
            orig = xxh3_mod._fold_unroll
            xxh3_mod._fold_unroll = lambda _n, _f=factor: min(_f, max(1, _n))
            try:
                got = ints(
                    jax.jit(fold_record_hashes_masked)(scalar(start), padded, mask)
                )
            finally:
                xxh3_mod._fold_unroll = orig
            assert int(got) == want, (n, pad, factor)


def test_fold_unroll_env_override(monkeypatch):
    """The env knob clamps to the scan length and a malformed value
    degrades to the default instead of crashing mid-trace."""
    from s2_verification_tpu.ops.xxh3 import _fold_unroll

    monkeypatch.setenv("S2VTPU_FOLD_UNROLL", "64")
    assert _fold_unroll(4) == 4
    assert _fold_unroll(128) == 64
    # The suite usually pins cpu (rolled default); S2VTPU_TEST_PLATFORM
    # can run it on an accelerator, where the default is 8.
    default = 1 if jax.default_backend() == "cpu" else 8
    monkeypatch.setenv("S2VTPU_FOLD_UNROLL", "not-a-number")
    assert _fold_unroll(16) == default
    monkeypatch.delenv("S2VTPU_FOLD_UNROLL")
    assert _fold_unroll(16) == default


def test_vmapped_fold():
    # The search folds one batch of hashes from many candidate states.
    starts = rand64(50)
    hs = rand64(16)
    mask = np.array([True] * 12 + [False] * 4)
    hs_dev = u(hs)
    batched = jax.vmap(lambda s: fold_record_hashes_masked(s, hs_dev, mask))
    got = ints(batched(u(starts)))
    want = [hashing.fold_record_hashes(s, hs[:12]) for s in starts]
    np.testing.assert_array_equal(got, want)
