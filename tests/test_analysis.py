"""verifylint: fixture corpus, suppressions, baseline ratchet, whole-tree
smoke, and regression tests for the defects the first full run surfaced.

The fixture mini-trees under ``tests/fixtures/lint/`` carry
``# expect: <rule>`` annotations on the exact lines each rule must anchor
to; ``test_fixture_corpus_exact`` holds the suite to them bidirectionally
(every expectation fires, nothing else does).  ``scripts/lint_check.py``
runs the same contract as a standalone gate.
"""

from __future__ import annotations

import json
import os
import re
import threading

import pytest

from s2_verification_tpu.analysis import (
    ERROR,
    Finding,
    LintEngine,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from s2_verification_tpu.analysis.engine import (
    TreeContext,
    discover_files,
    scan_suppressions,
)
from s2_verification_tpu.analysis.event_schema import render_events_md

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([\w\-, ]+?)\s*$")
_EXPECT_FILE_RE = re.compile(r"#\s*expect-file:\s*([\w\-]+)")


def _expectations(root: str):
    exact, file_level = [], []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root).replace(os.sep, "/")
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    m = _EXPECT_RE.search(line)
                    if m:
                        exact.extend((rel, i, r.strip()) for r in m.group(1).split(","))
                        continue
                    m = _EXPECT_FILE_RE.search(line)
                    if m:
                        file_level.append((rel, m.group(1)))
    return exact, file_level


@pytest.fixture(scope="module")
def tree_result():
    return LintEngine(os.path.join(FIXTURES, "tree")).run(paths=["."])


@pytest.fixture(scope="module")
def notable_result():
    return LintEngine(os.path.join(FIXTURES, "tree_notable")).run(paths=["."])


@pytest.fixture(scope="module")
def real_tree_result():
    return LintEngine(REPO).run()


# --------------------------------------------------------------------------
# fixture corpus


ALL_RULES = sorted(
    [
        "jit-unwrapped",
        "jit-in-loop",
        "jit-unhashable-static",
        "jit-traced-branch",
        "metric-open-label",
        "metric-name",
        "concurrency-unlocked-write",
        "event-never-emitted",
        "event-field-unwritten",
        "protocol-no-table",
        "protocol-unknown-op",
        "protocol-unknown-field",
        "protocol-missing-required",
        "protocol-unguarded-read",
        "protocol-unsigned-mismatch",
        "parse-error",
    ]
)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_every_rule_fires_on_fixtures(rule, tree_result, notable_result):
    fired = {f.rule for f in tree_result.findings} | {
        f.rule for f in notable_result.findings
    }
    assert rule in fired


@pytest.mark.parametrize("tree", ["tree", "tree_notable"])
def test_fixture_corpus_exact(tree, tree_result, notable_result):
    """Bidirectional: every annotation fires at its line, nothing else fires."""
    res = tree_result if tree == "tree" else notable_result
    root = os.path.join(FIXTURES, tree)
    exact, file_level = _expectations(root)
    got = [(f.path, f.line, f.rule) for f in res.findings]
    unmatched = list(got)
    missing = []
    for e in exact:
        if e in unmatched:
            unmatched.remove(e)
        else:
            missing.append(e)
    for rel, rule in file_level:
        hit = next((g for g in unmatched if g[0] == rel and g[2] == rule), None)
        if hit is not None:
            unmatched.remove(hit)
        else:
            missing.append((rel, None, rule))
    assert not missing, f"annotated findings that did not fire: {missing}"
    assert not unmatched, f"findings with no annotation: {unmatched}"


def test_fixture_suppressions_counted(tree_result):
    # client.py, jit_rules.py, metric_rules.py, threads_rules.py: one each
    assert tree_result.suppressed == 4


def test_all_findings_are_errors(tree_result, notable_result):
    for f in tree_result.findings + notable_result.findings:
        assert f.severity == ERROR


# --------------------------------------------------------------------------
# suppression scanning


def test_scan_suppressions_same_line_and_shield():
    text = (
        "x = 1  # verifylint: disable=metric-open-label\n"
        "# verifylint: disable=jit-unwrapped,jit-in-loop\n"
        "y = 2\n"
        "# verifylint: disable-file=concurrency-unlocked-write\n"
    )
    per_line, file_level = scan_suppressions(text)
    assert per_line[1] == {"metric-open-label"}
    # a comment-only directive shields its own line AND the next
    assert per_line[2] == {"jit-unwrapped", "jit-in-loop"}
    assert per_line[3] == {"jit-unwrapped", "jit-in-loop"}
    assert file_level == {"concurrency-unlocked-write"}


def test_suppress_all(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "# verifylint: disable-file=all\n"
        "import jax\n"
        "bad = jax.jit(len)\n"
    )
    res = LintEngine(str(tmp_path)).run(rel_paths=["mod.py"])
    assert res.findings == []
    assert res.suppressed == 1


# --------------------------------------------------------------------------
# baseline ratchet


def _finding(msg: str, line: int = 3) -> Finding:
    return Finding("metric-open-label", ERROR, "pkg/mod.py", line, msg)


def test_ratchet_new_error_fails_baselined_passes(tmp_path):
    old = _finding("old debt")
    new = _finding("fresh regression")
    path = str(tmp_path / "baseline.json")
    write_baseline([old], path)
    ratchet = apply_baseline([old, new], load_baseline(path))
    assert [f.message for f in ratchet.new_errors] == ["fresh regression"]
    assert [f.message for f in ratchet.baselined] == ["old debt"]
    assert ratchet.stale_keys == []


def test_ratchet_fixed_finding_goes_stale(tmp_path):
    old = _finding("old debt")
    path = str(tmp_path / "baseline.json")
    write_baseline([old], path)
    ratchet = apply_baseline([], load_baseline(path))
    assert ratchet.new_errors == []
    assert ratchet.stale_keys == [old.key]


def test_ratchet_keys_are_line_independent_but_count_bounded(tmp_path):
    path = str(tmp_path / "baseline.json")
    write_baseline([_finding("dup", line=10)], path)
    moved = _finding("dup", line=99)  # same key, shuffled line: still covered
    ratchet = apply_baseline([moved], load_baseline(path))
    assert ratchet.new_errors == []
    # a second occurrence of the same key exceeds the baselined count
    ratchet = apply_baseline([moved, _finding("dup", line=100)], load_baseline(path))
    assert len(ratchet.new_errors) == 1


def test_write_baseline_preserves_justifications(tmp_path):
    path = str(tmp_path / "baseline.json")
    f = _finding("kept debt")
    write_baseline([f], path, {f.key: "operator-bounded label"})
    write_baseline([f], path)  # rewrite without passing justifications
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert doc["findings"][0]["justification"] == "operator-bounded label"


# --------------------------------------------------------------------------
# caching + partial scans


def test_cache_round_trip(tmp_path, tree_result):
    cache = str(tmp_path / "cache.json")
    root = os.path.join(FIXTURES, "tree")
    first = LintEngine(root, cache_path=cache).run(paths=["."])
    second = LintEngine(root, cache_path=cache).run(paths=["."])
    assert first.cache_hits == 0
    assert second.cache_hits > 0
    assert [f.to_dict() for f in first.findings] == [
        f.to_dict() for f in second.findings
    ]
    assert [f.to_dict() for f in first.findings] == [
        f.to_dict() for f in tree_result.findings
    ]


def test_partial_scan_keeps_tree_context():
    """A scoped run (lint --changed) still parses the whole package, so
    tree passes don't report consumers of elsewhere-emitted events."""
    res = LintEngine(REPO).run(rel_paths=["s2_verification_tpu/service/stats.py"])
    assert not [
        f
        for f in res.findings
        if f.rule in ("event-never-emitted", "event-field-unwritten")
    ]
    for f in res.findings:
        assert f.path == "s2_verification_tpu/service/stats.py"


# --------------------------------------------------------------------------
# whole-tree smoke + docs


def test_real_tree_no_new_errors(real_tree_result):
    baseline = load_baseline(os.path.join(REPO, ".verifylint-baseline.json"))
    ratchet = apply_baseline(real_tree_result.errors, baseline)
    assert not ratchet.new_errors, [f.key for f in ratchet.new_errors]
    assert not ratchet.stale_keys


def test_events_md_up_to_date():
    ctx = TreeContext(REPO, discover_files(REPO))
    with open(os.path.join(REPO, "docs", "EVENTS.md"), encoding="utf-8") as f:
        assert f.read() == render_events_md(ctx)


# --------------------------------------------------------------------------
# regression tests for the findings fixed in-tree


def test_prober_transition_fires_once_under_contention():
    """probe_once is both the poller tick and a public entry; the status
    read-modify-write is locked so a transition fires on_change once."""
    from s2_verification_tpu.obs.probe import HealthProber

    fired = []
    fired_lock = threading.Lock()

    def on_change(name, up):
        with fired_lock:
            fired.append((name, up))

    prober = HealthProber({"b0": lambda: True}, on_change=on_change)
    n = 8
    barrier = threading.Barrier(n)

    def hammer():
        barrier.wait()
        prober.probe_once()

    threads = [threading.Thread(target=hammer) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # first observation is one transition (None -> up), seen exactly once
    assert fired == [("b0", True)]


def test_prober_transition_sequence():
    from s2_verification_tpu.obs.probe import HealthProber

    state = {"up": True}
    fired = []
    prober = HealthProber(
        {"b0": lambda: state["up"]}, on_change=lambda n, up: fired.append(up)
    )
    prober.probe_once()
    prober.probe_once()  # steady: no edge
    state["up"] = False
    prober.probe_once()
    state["up"] = True
    prober.probe_once()
    assert fired == [True, False, True]
    assert prober.status == {"b0": True}


def test_dashboard_throughput_deltas_locked():
    """sample_once's prev_* baseline is read-then-write under the lock;
    sequential ticks must diff against the moving baseline exactly once."""
    from s2_verification_tpu.obs.dashboard import Dashboard
    from s2_verification_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    completed = reg.counter("verifyd_jobs_completed_total", "test")
    times = iter([0.0, 1.0, 2.0, 4.0])
    dash = Dashboard(reg, time_fn=lambda: next(times))
    assert dash.sample_once()["throughput"] == 0.0  # no baseline yet
    completed.inc(5)
    assert dash.sample_once()["throughput"] == 5.0  # 5 jobs / 1 s
    assert dash.sample_once()["throughput"] == 0.0  # baseline advanced
    completed.inc(4)
    assert dash.sample_once()["throughput"] == 2.0  # 4 jobs / 2 s
    assert len(dash.payload()["t"]) == 4


def test_stats_backend_label_folded():
    """Sized backend values must fold to the engine family before they
    become a label — no timeseries per mesh size / device ordinal."""
    from s2_verification_tpu.obs.metrics import MetricsRegistry
    from s2_verification_tpu.service.stats import ServiceStats

    reg = MetricsRegistry()
    stats = ServiceStats(sink=None, registry=reg)
    for backend in ("device-mesh[4]", "device-mesh[8]", "device-3", "native", "zzz-custom"):
        stats.emit("done", verdict=0, wall_s=0.1, backend=backend)
    wall = reg.get("verifyd_wall_seconds")
    assert wall.counts(backend="device-mesh")[2] == 2
    assert wall.counts(backend="device")[2] == 1
    assert wall.counts(backend="native")[2] == 1
    assert wall.counts(backend="other")[2] == 1
    assert wall.counts(backend="device-mesh[4]")[2] == 0


def test_stats_writer_label_folded():
    from s2_verification_tpu.obs.metrics import MetricsRegistry
    from s2_verification_tpu.service.stats import ServiceStats

    reg = MetricsRegistry()
    stats = ServiceStats(sink=None, registry=reg)
    stats.emit("writer_degraded", writer="surprise-writer-17")
    g = reg.get("verifyd_writer_degraded")
    assert g.value(writer="other") == 1
    stats.emit("writer_recovered", writer="surprise-writer-17")
    assert g.value(writer="other") == 0
    stats.emit("writer_degraded", writer="journal")
    assert g.value(writer="journal") == 1
