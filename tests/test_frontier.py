"""Differential tests: frontier BFS vs the Wing–Gong DFS oracle."""

import random

import pytest

from helpers import H, fold
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.frontier import check_frontier, check_frontier_auto
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.collector.collect import CollectConfig, collect_history
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from test_oracle_bruteforce import random_history


@pytest.mark.parametrize("auto_close", [True, False])
def test_frontier_matches_dfs_on_random_histories(auto_close):
    rng = random.Random(0xF00D)
    agree = 0
    for trial in range(200):
        h = random_history(rng)
        hist = prepare(h.events)
        want = check(hist).outcome
        got = check_frontier(hist, auto_close=auto_close).outcome
        assert got == want, f"trial {trial}: frontier={got} dfs={want}"
        agree += 1
    assert agree == 200


@pytest.mark.parametrize("workflow", ["regular", "match-seq-num", "fencing"])
@pytest.mark.parametrize("seed", range(4))
def test_frontier_on_collected_histories(workflow, seed):
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=30,
            workflow=workflow,
            seed=seed,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.3, max_latency=0.001),
        )
    )
    hist = prepare(events)
    assert check_frontier_auto(hist, beam_width=512).outcome == CheckOutcome.OK
    assert check(hist).outcome == CheckOutcome.OK


def test_frontier_rejects_corrupted_collected_history():
    from s2_verification_tpu.utils.events import LabeledEvent, ReadSuccess

    events = collect_history(
        CollectConfig(
            num_concurrent_clients=3,
            num_ops_per_client=20,
            workflow="regular",
            seed=1,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.2, max_latency=0.001),
        )
    )
    tampered = []
    done = False
    for e in events:
        if not done and isinstance(e.event, ReadSuccess) and e.event.tail > 0:
            e = LabeledEvent(
                ReadSuccess(tail=e.event.tail, stream_hash=e.event.stream_hash ^ 1),
                e.client_id,
                e.op_id,
            )
            done = True
        tampered.append(e)
    assert done
    hist = prepare(tampered)
    assert check_frontier(hist).outcome == CheckOutcome.ILLEGAL
    assert check(hist).outcome == CheckOutcome.ILLEGAL


def test_auto_close_handles_many_open_ops():
    # Open match-seq-num appends whose guards are long dead: without
    # auto-close the frontier carries every subset of open ops; with it the
    # search stays narrow.  (This is the CPU-intractable shape of the
    # reference stress config.)
    h = H()
    tail = 0
    acc = 0
    # Establish a tail of 3 first, so the opens' guards are stale the moment
    # they become candidates (every reachable state has tail > match_seq_num).
    for i in range(3):
        rh = 200 + i
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    n_open = 10
    for i in range(n_open):
        # Each client appends with a dead guard, fails indefinitely, and
        # never finishes (client rotated away).
        h.call_append(100 + i, [i + 1], match=i % 3)
    for i in range(25):
        rh = 50 + i
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    h.read_ok(2, tail=tail, stream_hash=acc)
    hist = prepare(h.events)
    res = check_frontier(hist, collect_stats=True)
    assert res.outcome == CheckOutcome.OK
    stats = res.stats
    assert stats.auto_closed >= n_open
    # The frontier never needs to branch on the dead opens.
    assert stats.max_frontier <= 4

    # Sanity: the DFS agrees (it pays a price but these sizes are fine).
    assert check(hist).outcome == CheckOutcome.OK


def test_frontier_unknown_on_budget():
    # A history with genuinely live ambiguity can exceed a tiny frontier cap.
    h = H()
    for i in range(6):
        h.call_append(10 + i, [i + 1])  # unguarded opens: live forever
    h.append_ok(1, [99], tail=1)
    hist = prepare(h.events)
    res = check_frontier(hist, max_frontier=2)
    assert res.outcome == CheckOutcome.UNKNOWN


def test_frontier_witness_is_valid():
    # The frontier engine's accept-path witness (parity with the device
    # engine's): covers every op once, extends real time, keeps state sets
    # non-empty.
    import random

    from helpers import assert_valid_linearization as _assert_valid_linearization
    from test_oracle_bruteforce import random_history

    rng = random.Random(0xF17)
    checked = 0
    for _ in range(40):
        h = random_history(rng)
        hist = prepare(h.events)
        res = check_frontier(hist)
        if res.outcome == CheckOutcome.OK:
            assert res.linearization is not None
            _assert_valid_linearization(hist, res.linearization)
            checked += 1
    assert checked >= 5


def test_frontier_witness_opt_out_and_deepest():
    import random

    from test_oracle_bruteforce import random_history

    rng = random.Random(0xD33)
    saw_ok = saw_illegal = False
    for _ in range(60):
        h = random_history(rng)
        hist = prepare(h.events)
        res = check_frontier(hist, witness=False)
        if res.outcome == CheckOutcome.OK:
            assert res.linearization is None  # verdict-only mode
            saw_ok = True
        elif res.outcome == CheckOutcome.ILLEGAL and hist.ops:
            # deepest is the globally deepest committed prefix: a real
            # subset of ops, each index valid.
            assert all(0 <= j < len(hist.ops) for j in res.deepest)
            saw_illegal = True
    assert saw_ok and saw_illegal


def test_frontier_stats_fields_on_known_history():
    # Satellite regression: pin every FrontierStats field on a history
    # whose search shape is knowable by hand.  A single client appending
    # sequentially has exactly one state and one frontier node per layer:
    # layers == ops, max_frontier == 1, nothing auto-closed or pruned.
    h = H()
    acc, tail = 0, 0
    for rh in (11, 22, 33, 44):
        h.append_ok(1, [rh], tail=tail + 1)
        acc = fold([rh], start=acc)
        tail += 1
    h.read_ok(1, tail=tail, stream_hash=acc)
    hist = prepare(h.events)
    res = check_frontier(hist, collect_stats=True)
    assert res.outcome == CheckOutcome.OK
    st = res.stats
    # One layer per linearized op plus the final layer that observes the
    # accept (no expansion happens there: expanded stays == ops).
    assert st.layers == len(hist.ops) + 1
    assert st.max_frontier == 1
    assert st.max_state_set == 1
    assert st.auto_closed == 0
    assert st.pruned == 0
    assert st.expanded == len(hist.ops)
    # collect_stats alone gathers no per-layer timeline (profile= does).
    assert st.timeline == []


def test_frontier_stats_counts_auto_closed_dead_guard():
    # One open append with a guard already dead at the open: the frontier
    # auto-closes it instead of branching, and the accountant sees it.
    h = H()
    h.append_ok(1, [5], tail=1)  # bumps the match seq past 0
    h.call_append(2, [7], match=0)  # guard 0 is dead: must fail, stays open
    h.read_ok(1, tail=1, stream_hash=fold([5]))
    hist = prepare(h.events)
    res = check_frontier(hist, collect_stats=True)
    assert res.outcome == CheckOutcome.OK
    assert res.stats.auto_closed >= 1


def test_frontier_profile_timeline_shape():
    # profile=True implies stats collection and fills one entry per layer
    # with the documented keys, cumulative elapsed, and a frontier column
    # that matches the recorded maximum.
    h = H()
    acc, tail = 0, 0
    for i in range(3):
        h.append_ok(1 + (i % 2), [100 + i], tail=tail + 1)
        acc = fold([100 + i], start=acc)
        tail += 1
    h.read_ok(1, tail=tail, stream_hash=acc)
    hist = prepare(h.events)
    res = check_frontier(hist, profile=True)
    assert res.outcome == CheckOutcome.OK
    st = res.stats
    assert st is not None  # profile implies collect_stats
    tl = st.timeline
    assert len(tl) == st.layers
    assert [e["layer"] for e in tl] == list(range(1, st.layers + 1))
    for e in tl:
        assert set(e) >= {"layer", "frontier", "states", "auto_closed", "elapsed_s"}
        assert e["frontier"] >= 1
        assert e["states"] >= 1
        assert e["elapsed_s"] >= 0.0
    assert max(e["frontier"] for e in tl) == st.max_frontier
    assert max(e["states"] for e in tl) == st.max_state_set
    assert sum(e["auto_closed"] for e in tl) == st.auto_closed
    # elapsed is cumulative since search start: non-decreasing.
    elapsed = [e["elapsed_s"] for e in tl]
    assert elapsed == sorted(elapsed)


def test_frontier_auto_passes_profile_through():
    h = H()
    h.append_ok(1, [9], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([9]))
    hist = prepare(h.events)
    res = check_frontier_auto(hist, profile=True)
    assert res.outcome == CheckOutcome.OK
    assert res.stats is not None and len(res.stats.timeline) == res.stats.layers
