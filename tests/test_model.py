"""Unit tests for the S2 stream model's step truth table (SURVEY.md §2.1,
golang/s2-porcupine/main.go:264-340)."""

from s2_verification_tpu.models.stream import (
    APPEND,
    CHECK_TAIL,
    INIT_STATE,
    READ,
    StreamInput,
    StreamOutput,
    StreamState,
    step,
    step_set,
)
from s2_verification_tpu.utils.hashing import fold_record_hashes

S0 = StreamState(tail=4, stream_hash=77, fencing_token=None)
ST = StreamState(tail=4, stream_hash=77, fencing_token="tok")


def appended(state, hashes, token=None):
    return StreamState(
        tail=state.tail + len(hashes),
        stream_hash=fold_record_hashes(state.stream_hash, hashes),
        fencing_token=token if token is not None else state.fencing_token,
    )


def ap_in(hashes, set_tok=None, batch_tok=None, match=None):
    return StreamInput(
        input_type=APPEND,
        set_fencing_token=set_tok,
        batch_fencing_token=batch_tok,
        match_seq_num=match,
        num_records=len(hashes),
        record_hashes=tuple(hashes),
    )


def test_append_success():
    hs = (11, 22)
    out = StreamOutput(tail=6)
    assert step(S0, ap_in(hs), out) == [appended(S0, hs)]


def test_append_success_wrong_tail_is_illegal():
    assert step(S0, ap_in((11, 22)), StreamOutput(tail=7)) == []


def test_append_definite_failure_is_noop():
    out = StreamOutput(failure=True, definite_failure=True)
    assert step(S0, ap_in((11, 22), match=999), out) == [S0]


def test_append_indefinite_failure_forks():
    out = StreamOutput(failure=True)
    hs = (11, 22)
    assert step(S0, ap_in(hs), out) == [appended(S0, hs), S0]


def test_append_indefinite_failure_guarded_by_match_seq_num():
    out = StreamOutput(failure=True)
    assert step(S0, ap_in((11,), match=3), out) == [S0]  # mismatch: no fork
    hs = (11,)
    assert step(S0, ap_in(hs, match=4), out) == [appended(S0, hs), S0]


def test_append_indefinite_failure_guarded_by_token():
    out = StreamOutput(failure=True)
    # No token on the stream: supplied batch token cannot match.
    assert step(S0, ap_in((11,), batch_tok="tok"), out) == [S0]
    # Matching token: fork.
    hs = (11,)
    assert step(ST, ap_in(hs, batch_tok="tok"), out) == [appended(ST, hs), ST]
    # Mismatching token: no fork.
    assert step(ST, ap_in((11,), batch_tok="other"), out) == [ST]


def test_append_success_guards():
    # Success with a mismatched token or seq num is an illegal observation.
    assert step(S0, ap_in((11,), batch_tok="tok"), StreamOutput(tail=5)) == []
    assert step(ST, ap_in((11,), batch_tok="other"), StreamOutput(tail=5)) == []
    assert step(S0, ap_in((11,), match=3), StreamOutput(tail=5)) == []
    hs = (11,)
    assert step(ST, ap_in(hs, batch_tok="tok"), StreamOutput(tail=5)) == [appended(ST, hs)]


def test_append_sets_fencing_token():
    hs = (99,)
    got = step(S0, ap_in(hs, set_tok="new"), StreamOutput(tail=5))
    assert got == [appended(S0, hs, token="new")]
    # Setting a token on a fenced stream requires the batch token to match
    # only if one was supplied; set alone replaces it.
    got = step(ST, ap_in(hs, set_tok="new"), StreamOutput(tail=5))
    assert got == [appended(ST, hs, token="new")]


def test_empty_string_token_distinct_from_none():
    s_empty = StreamState(4, 77, "")
    out = StreamOutput(failure=True)
    # none-token stream vs "" batch token: mismatch (Go nil vs pointer-to-"").
    assert step(S0, ap_in((1,), batch_tok=""), out) == [S0]
    hs = (1,)
    assert step(s_empty, ap_in(hs, batch_tok=""), out) == [appended(s_empty, hs), s_empty]


def test_read_checks_hash_and_tail():
    rd = StreamInput(input_type=READ)
    assert step(S0, rd, StreamOutput(tail=4, stream_hash=77)) == [S0]
    assert step(S0, rd, StreamOutput(tail=4, stream_hash=78)) == []
    assert step(S0, rd, StreamOutput(tail=5, stream_hash=77)) == []
    assert step(S0, rd, StreamOutput(failure=True, definite_failure=True)) == [S0]


def test_check_tail():
    ct = StreamInput(input_type=CHECK_TAIL)
    assert step(S0, ct, StreamOutput(tail=4)) == [S0]
    assert step(S0, ct, StreamOutput(tail=3)) == []
    assert step(S0, ct, StreamOutput(failure=True, definite_failure=True)) == [S0]


def test_step_set_unions_and_dedups():
    out = StreamOutput(failure=True)
    hs = (11,)
    forked = step_set([S0], ap_in(hs), out)
    assert forked == [appended(S0, hs), S0]
    # Stepping the forked set through a check-tail success filters it.
    ct = StreamInput(input_type=CHECK_TAIL)
    assert step_set(forked, ct, StreamOutput(tail=4)) == [S0]
    assert step_set(forked, ct, StreamOutput(tail=5)) == [appended(S0, hs)]
    # Dedup: two identical paths collapse.
    dup = step_set([S0, S0], ct, StreamOutput(tail=4))
    assert dup == [S0]


def test_init_state():
    assert INIT_STATE == StreamState(0, 0, None)
