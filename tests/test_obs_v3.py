"""Obs v3 tests: runtime introspection — the JIT-compile tracker and its
retrace-storm latch, the child→parent compile fold, resource-sampler ring
bounds, OpenMetrics exemplar exposition (and its absence from the classic
format), registry render under concurrent registration, the /dashboard
surface over a live daemon, the `dash` CLI, the profiles CSV escaping
regression, and the doctor's resource timeline.

Runs under the session-wide ``JAX_PLATFORMS=cpu`` pin (conftest.py);
everything here is in-process and fast — the cross-process compile
harvest is exercised end to end by ``scripts/obs_check.py`` (`make obs`).
"""

import csv
import io
import json
import threading
import urllib.error
import urllib.request

import numpy as np

from helpers import H, fold
from s2_verification_tpu.cli import main as cli_main
from s2_verification_tpu.obs import (
    Dashboard,
    FlightRecorder,
    JitIntrospector,
    MetricsRegistry,
    ResourceSampler,
    Tracer,
    job_context,
    observe_jit,
    postmortem,
    render_postmortem,
)
from s2_verification_tpu.obs.metrics import OPENMETRICS_CONTENT_TYPE
from s2_verification_tpu.service.client import VerifydClient
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.stats import ServiceStats
from s2_verification_tpu.utils import events as ev

# -- the fake jit site -------------------------------------------------------


def _site(tracker, name="fake_site"):
    calls = []

    @observe_jit(name, tracker=tracker)
    def fn(x, flag=True):
        calls.append(x)
        return x

    return fn, calls


def test_compile_tracker_counts_compiles_hits_and_retraces():
    tr = JitIntrospector()
    fn, calls = _site(tr)
    a = np.zeros((4, 8), dtype=np.float32)
    with job_context(shape="64x5x8"):
        fn(a)  # first signature -> compile
        fn(a)  # same signature -> hit
        fn(np.ones((4, 8), dtype=np.float32))  # same dtype+shape -> hit
        fn(np.zeros((2, 2), dtype=np.int32))  # new signature -> retrace
    assert len(calls) == 4  # the wrapper always calls through
    snap = tr.snapshot()
    assert snap["compiles"] == {"fake_site\t64x5x8": 2}
    assert snap["retraces"] == {"fake_site\t64x5x8": 1}
    assert snap["hits"] == {"64x5x8": 2}
    assert snap["misses"] == {"64x5x8": 2}
    assert snap["signatures"] == {"fake_site": 2}
    assert snap["compile_wall_s"]["fake_site"] >= 0.0


def test_static_kwarg_changes_are_their_own_signatures():
    tr = JitIntrospector()
    fn, _ = _site(tr)
    a = np.zeros((3,), dtype=np.float32)
    fn(a, flag=True)
    fn(a, flag=False)  # static retoggle -> jit would retrace; so do we
    fn(a, flag=True)  # cached again
    snap = tr.snapshot()
    assert sum(snap["compiles"].values()) == 2
    assert sum(snap["hits"].values()) == 1


def test_compile_records_span_on_the_context_tracer():
    tr = JitIntrospector()
    fn, _ = _site(tr)
    tracer = Tracer(64)
    with job_context(job=7, shape="s", trace_id="ab" * 16, tracer=tracer):
        fn(np.zeros((2,), dtype=np.float32))
    spans = [
        e
        for e in tracer.export()["traceEvents"]
        if e.get("ph") == "X" and e.get("name") == "jit.compile"
    ]
    assert len(spans) == 1
    assert spans[0]["tid"] == 7
    assert spans[0]["args"]["site"] == "fake_site"
    assert spans[0]["args"]["trace_id"] == "ab" * 16


def test_retrace_storm_is_latched_and_reaches_the_event_stream():
    stats = ServiceStats(None)
    tr = JitIntrospector()
    tr.attach(registry=stats.registry, stats=stats, storm_threshold=2)
    fn, _ = _site(tr)
    with job_context(shape="stormy"):
        for n in (2, 3, 4, 5):  # four distinct signatures, one shape bucket
            fn(np.zeros((n,), dtype=np.float32))
    snap = tr.snapshot()
    assert snap["storms"] == [
        {"site": "fake_site", "shape": "stormy", "compiles": 3}
    ]
    # Exactly one event despite two compiles past the threshold: latched.
    assert stats.snapshot()["retrace_storms"] == 1
    rendered = stats.registry.render()
    assert "verifyd_retrace_storms_total 1" in rendered
    assert 'verifyd_jit_retraces_total{site="fake_site",shape="stormy"} 3' in rendered


def test_fold_merges_child_snapshot_and_retrips_the_storm():
    stats = ServiceStats(None)
    parent = JitIntrospector()
    parent.attach(registry=stats.registry, stats=stats, storm_threshold=2)

    child = JitIntrospector()
    fn, _ = _site(child, name="regrow")
    with job_context(shape="64x5x8"):
        for n in (2, 3, 4):
            fn(np.zeros((n,), dtype=np.float32))
    harvest = child.snapshot_and_reset()
    # The reset half: a restarted attempt starts from zero.
    assert child.snapshot()["compiles"] == {}

    parent.fold(harvest)
    snap = parent.snapshot()
    assert snap["compiles"] == {"regrow\t64x5x8": 3}
    assert snap["hits"] == {}
    assert stats.snapshot()["retrace_storms"] == 1
    # Folding the same counts again adds, but the latch holds.
    parent.fold(harvest)
    assert parent.snapshot()["compiles"] == {"regrow\t64x5x8": 6}
    assert stats.snapshot()["retrace_storms"] == 1


def test_attach_replays_accumulated_counts_into_a_fresh_registry():
    tr = JitIntrospector()
    fn, _ = _site(tr)
    with job_context(shape="pre"):
        fn(np.zeros((2,), dtype=np.float32))
        fn(np.zeros((2,), dtype=np.float32))
    reg = MetricsRegistry()
    tr.attach(registry=reg)
    text = reg.render()
    assert 'verifyd_jit_compiles_total{site="fake_site",shape="pre"} 1' in text
    assert 'verifyd_jit_cache_hits_total{shape="pre"} 1' in text


# -- resource sampler --------------------------------------------------------


def test_resource_sampler_ring_is_bounded_and_updates_gauges():
    reg = MetricsRegistry()
    s = ResourceSampler(reg, interval_s=60.0, capacity=3)
    for _ in range(7):
        sample = s.sample_once()
    assert sample["rss_bytes"] > 0
    assert sample["threads"] >= 1
    assert sample["cpu_s"] >= 0.0
    ring = s.ring()
    assert len(ring) == 3  # bounded: the four oldest fell off
    snap = s.snapshot()
    assert snap["samples"] == 7 and snap["retained"] == 3
    assert snap["last"]["rss_bytes"] == sample["rss_bytes"]
    text = reg.render()
    assert "verifyd_resource_rss_bytes %d" % sample["rss_bytes"] in text
    assert "verifyd_resource_threads" in text


def test_resource_sampler_feeds_the_flight_recorder(tmp_path):
    rec = FlightRecorder(str(tmp_path / "flight"))
    s = ResourceSampler(None, interval_s=60.0, recorder=rec)
    s.sample_once()
    s.sample_once()
    rec.close()
    pm = postmortem(str(tmp_path))
    assert pm["resource_samples"] == 2
    assert pm["resources"][-1]["rss_bytes"] > 0
    report = render_postmortem(pm)
    assert "resource timeline" in report
    assert "rss=" in report


# -- exemplars ---------------------------------------------------------------


def test_openmetrics_exemplars_render_and_classic_text_stays_clean():
    reg = MetricsRegistry()
    hist = reg.histogram(
        "demo_seconds", buckets=(0.1, 1.0), labelnames=("backend",)
    )
    tid = "deadbeef" * 4
    hist.observe(0.05, exemplar=tid, backend="native")
    hist.observe(0.5, backend="native")  # no exemplar on this bucket
    om = reg.render_openmetrics()
    assert om.rstrip().endswith("# EOF")
    ex_lines = [l for l in om.splitlines() if "# {" in l]
    assert len(ex_lines) == 1
    line = ex_lines[0]
    assert 'le="0.1"' in line
    assert '# {trace_id="%s"} 0.05' % tid in line
    # OpenMetrics counter families drop _total from HELP/TYPE only.
    reg.counter("demo_jobs_total").inc()
    om = reg.render_openmetrics()
    assert "# TYPE demo_jobs counter" in om
    assert "demo_jobs_total 1" in om
    # The classic 0.0.4 exposition never shows exemplar syntax.
    classic = reg.render()
    assert "# {" not in classic
    assert "# EOF" not in classic
    assert "# TYPE demo_jobs_total counter" in classic


def test_histogram_observe_without_exemplar_keeps_counts_consistent():
    reg = MetricsRegistry()
    hist = reg.histogram("h_seconds", buckets=(1.0,))
    hist.observe(0.5)
    hist.observe(2.0, exemplar="ab" * 16)
    cum, total, count = hist.counts()
    assert count == 2
    assert total == 2.5
    assert cum == [1, 2]  # one under le=1.0, both under +Inf
    snap = reg.snapshot()["histograms"]["h_seconds"]
    assert snap["count"] == 2
    # The exemplar rides the snapshot, keyed by its bucket boundary.
    assert snap["exemplars"]["+Inf"]["trace_id"] == "ab" * 16


def test_registry_render_is_safe_against_concurrent_registration():
    reg = MetricsRegistry()
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            try:
                reg.counter("churn_%d_total" % (i % 50)).inc()
                reg.gauge("churn_g_%d" % (i % 50)).set(i)
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            text = reg.render()
            assert isinstance(text, str)
            reg.render_openmetrics()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors


# -- live daemon: /dashboard + stats op + dash CLI ---------------------------


def _good_history() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def test_dashboard_and_introspection_over_a_live_daemon(tmp_path, capsys):
    cfg = VerifydConfig(
        socket_path=str(tmp_path / "v.sock"),
        out_dir=str(tmp_path / "viz"),
        no_viz=True,
        stats_log=None,
        device="off",
        metrics_port=0,
        resource_sample_s=0.1,
        dashboard_sample_s=0.1,
    )
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path)
        assert client.submit(_good_history(), client="v3")["verdict"] == 0
        # Let the dashboard thread take at least one post-job sample.
        for _ in range(100):
            if daemon.dashboard.payload()["retained"] >= 2:
                break
            threading.Event().wait(0.05)
        port = daemon.metrics_port

        html = (
            urllib.request.urlopen(
                "http://127.0.0.1:%d/dashboard" % port, timeout=5
            )
            .read()
            .decode()
        )
        assert html.startswith("<!DOCTYPE html>")
        assert "<svg" in html and "polyline" in html
        assert "throughput" in html and "host RSS" in html

        feed = json.loads(
            urllib.request.urlopen(
                "http://127.0.0.1:%d/dashboard.json" % port, timeout=5
            ).read()
        )
        assert feed["retained"] >= 2
        assert set(feed["series"]) >= {"throughput", "queue_depth", "rss_mb"}
        assert len(feed["series"]["rss_mb"]) == feed["retained"]
        assert any(v > 0 for v in feed["series"]["rss_mb"])

        # Content negotiation: the OpenMetrics variant ends with EOF, the
        # classic variant never contains it.
        req = urllib.request.Request(
            "http://127.0.0.1:%d/metrics" % port,
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.headers["Content-Type"] == OPENMETRICS_CONTENT_TYPE
            om = resp.read().decode()
        assert om.rstrip().endswith("# EOF")
        assert 'trace_id="' in om  # the served job left an exemplar
        classic = (
            urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port, timeout=5
            )
            .read()
            .decode()
        )
        assert "# EOF" not in classic

        # The stats op carries the introspection section.
        snap = client.stats()
        intro = snap["introspection"]
        assert "jit" in intro and "storm_threshold" in intro["jit"]
        assert intro["resources"]["last"]["rss_bytes"] > 0

        # One dash frame against the same daemon.
        rc = cli_main(
            [
                "dash",
                "--socket",
                cfg.socket_path,
                "--iterations",
                "1",
                "--interval",
                "0.1",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "verifyd dash" in out
        assert "throughput" in out and "rss" in out


def test_dashboard_routes_404_without_a_dashboard(tmp_path):
    cfg = VerifydConfig(
        socket_path=str(tmp_path / "v.sock"),
        out_dir=str(tmp_path / "viz"),
        no_viz=True,
        stats_log=None,
        device="off",
        metrics_port=0,
        dashboard_sample_s=0.0,  # explicit opt-out
    )
    with Verifyd(cfg) as daemon:
        try:
            urllib.request.urlopen(
                "http://127.0.0.1:%d/dashboard" % daemon.metrics_port,
                timeout=5,
            )
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:  # pragma: no cover
            raise AssertionError("expected 404")


def test_dashboard_sampling_is_registry_driven():
    reg = MetricsRegistry()
    completed = reg.counter("verifyd_jobs_completed_total")
    ts = iter(float(i) for i in range(100))
    d = Dashboard(reg, interval_s=1.0, capacity=4, time_fn=lambda: next(ts))
    d.sample_once()
    completed.inc(5)
    d.sample_once()  # 5 completions over a 1s tick → 5 jobs/s
    assert d.payload()["series"]["throughput"][-1] == 5.0
    for _ in range(5):
        d.sample_once()
    p = d.payload()
    assert p["retained"] == 4  # bounded ring: oldest samples fell off
    assert len(p["t"]) == 4
    assert all(len(s) == 4 for s in p["series"].values())
    html = d.render_html()
    assert "<svg" in html
    assert json.loads(d.render_json())["retained"] == 4


# -- profiles CSV escaping (regression) --------------------------------------


def test_profiles_csv_export_quotes_commas_and_serializes_containers():
    from s2_verification_tpu.cli import _PROFILE_COLUMNS, _export_profiles

    records = [
        {
            "t": 1.5,
            "job": 1,
            "client": 'ci,"weird" bot',
            "shape": "64x5x8,dense",
            "backend": "device-mesh[4]",
            "verdict": 0,
            "wall_s": 0.25,
            "queue_wait_s": 0.01,
            "lease_wait_s": 0.0,
            "ops": 64,
            "shards": {"n": 4, "note": 'a,b "c"'},
            "fp": "ff00",
        }
    ]
    buf = io.StringIO()
    _export_profiles(records, buf, "csv")
    text = buf.getvalue()
    # RFC 4180: embedded quotes doubled inside a quoted cell.
    assert '"ci,""weird"" bot"' in text
    rows = list(csv.reader(io.StringIO(text)))
    assert rows[0] == list(_PROFILE_COLUMNS)
    row = dict(zip(rows[0], rows[1]))
    assert row["client"] == 'ci,"weird" bot'
    assert row["shape"] == "64x5x8,dense"
    # Container cells come back as JSON, not a Python repr.
    assert json.loads(row["shards"]) == {"n": 4, "note": 'a,b "c"'}
