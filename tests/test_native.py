"""Differential tests: native C++ checker vs the Python oracle.

The native engine (native/s2check.cpp via checker/native.py) must agree with
checker/oracle.py verdict-for-verdict — the same relationship the reference
has between its Go model tests and the compiled porcupine search.
"""

import random

import pytest

from helpers import H, fold
from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.checker.oracle import CheckOutcome, check
from s2_verification_tpu.checker.native import check_native, native_available
from s2_verification_tpu.collector.collect import CollectConfig, collect_history
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from s2_verification_tpu.models.stream import step_set
from test_oracle_bruteforce import random_history

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library not buildable"
)

BATCH = [11, 22, 33]


def test_native_matches_oracle_on_random_histories():
    rng = random.Random(0xC0FFEE)
    for trial in range(400):
        h = random_history(rng)
        hist = prepare(h.events)
        want = check(hist)
        got = check_native(hist)
        assert got.outcome == want.outcome, f"trial {trial}"
        if want.ok:
            assert sorted(got.final_states) == sorted(want.final_states), (
                f"trial {trial}"
            )


def test_native_linearization_replays():
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=4,
            num_ops_per_client=25,
            workflow="fencing",
            seed=21,
            faults=FaultPlan.chaos(0.25),
        )
    )
    hist = prepare(events)
    res = check_native(hist)
    assert res.ok
    assert sorted(res.linearization) == list(range(len(hist.ops)))
    # Replaying the full order through the model must keep the state set
    # non-empty and land on the reported final states.
    states = None
    from s2_verification_tpu.models.stream import INIT_STATE

    states = [INIT_STATE]
    for idx in res.linearization:
        op = hist.ops[idx]
        states = step_set(states, op.inp, op.out)
        assert states, f"order dies at op {idx}"
    assert sorted(states) == sorted(res.final_states)


def test_native_rejects_corrupted_prefix():
    # TestReadDetectsCorruptedPrefix (main_test.go:317-342): right tail,
    # right last batch, corrupted earlier prefix hash.
    h = H()
    h.append_ok(1, BATCH, tail=3)
    h.append_ok(1, [44], tail=4)
    bad = fold([99, 98, 97] + [44])
    h.read_ok(1, tail=4, stream_hash=bad)
    assert check_native(prepare(h.events)).outcome == CheckOutcome.ILLEGAL


def test_native_time_budget_returns_unknown_or_verdict():
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=5,
            num_ops_per_client=40,
            workflow="regular",
            seed=3,
            faults=FaultPlan.chaos(0.2),
        )
    )
    hist = prepare(events)
    res = check_native(hist, time_budget_s=1e-9)
    assert res.outcome in (CheckOutcome.UNKNOWN, CheckOutcome.OK, CheckOutcome.ILLEGAL)
    full = check_native(hist)
    assert full.outcome == check(hist).outcome


def test_native_empty_history():
    res = check_native(prepare([]))
    assert res.ok and res.final_states


def test_native_deepest_matches_oracle_on_illegal():
    h = H()
    h.append_ok(1, BATCH, tail=3)
    h.append_ok(1, [44], tail=4)
    h.read_ok(1, tail=4, stream_hash=fold([99, 98, 97, 44]))
    hist = prepare(h.events)
    rn, ro = check_native(hist), check(hist)
    assert rn.outcome == ro.outcome == CheckOutcome.ILLEGAL
    assert sorted(rn.deepest) == sorted(ro.deepest)


def test_mixed_token_states_sort():
    # A tail/hash tie between a None-token and a str-token state must not
    # raise (plain tuple ordering would compare None < str).
    h = H()
    h.append_indefinite_fail(1, [], set_token="x")
    hist = prepare(h.events)
    rn, ro = check_native(hist), check(hist)
    assert rn.outcome == ro.outcome == CheckOutcome.OK
    assert sorted(rn.final_states) == sorted(ro.final_states)


def test_native_states_cap_retry():
    # Three independent indefinite appends with distinct hashes → 2^3 = 8
    # candidate final states.  A tiny output buffer must trigger the
    # truncation retry: the C side reports the FULL set size (not the
    # clamped write count), the wrapper reallocates and re-invokes.
    h = H()
    for i in range(3):
        h.append_indefinite_fail(i + 1, [100 + i])
    hist = prepare(h.events)
    full = check_native(hist)
    small = check_native(hist, _states_cap=1)
    assert full.ok and small.ok
    assert len(full.final_states) == 8
    assert sorted(small.final_states) == sorted(full.final_states)


def test_native_deepest_on_concurrent_illegal():
    # Two overlapping appends that both claim tail=1: exactly one can ever
    # be linearized, so deepest must contain one op (not be empty — the
    # engine tracks the best set reached during the search, oracle.py:173).
    from s2_verification_tpu.utils.events import AppendSuccess

    h = H()
    a = h.call_append(1, [11])
    b = h.call_append(2, [22])
    h.finish(1, a, AppendSuccess(tail=1))
    h.finish(2, b, AppendSuccess(tail=1))
    hist = prepare(h.events)
    rn, ro = check_native(hist), check(hist)
    assert rn.outcome == ro.outcome == CheckOutcome.ILLEGAL
    assert rn.deepest and sorted(rn.deepest) == sorted(ro.deepest)


def test_native_stats_populated():
    events = collect_history(
        CollectConfig(num_concurrent_clients=2, num_ops_per_client=10, seed=1)
    )
    hist = prepare(events)
    res = check_native(hist)
    assert res.ok and res.steps > 0
