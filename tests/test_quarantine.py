"""Poison-job quarantine: the per-fingerprint crash ledger across boots.

The scenario the subsystem exists for: a history whose verification
reliably kills the daemon (or its escalation child).  Without the
ledger, journal recovery faithfully replays the killer on every boot —
a crash loop.  With it, the fingerprint that was *running* at each
death accumulates crash counts across restarts and lands in quarantine
at the threshold, while innocent jobs that merely sat in the same
journal replay for free.
"""

import contextlib
import io
import json
import time

import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.service.cache import history_fingerprint
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.journal import JobJournal
from s2_verification_tpu.service.overload import QuarantineStore
from s2_verification_tpu.utils import events as ev

from helpers import H, fold

# -- fixtures ----------------------------------------------------------------


def _text(h: H) -> str:
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def good_history(base: int = 100) -> str:
    h = H()
    h.append_ok(1, [base + 1], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([base + 1]))
    return _text(h)


def _fingerprint(text: str) -> str:
    return history_fingerprint(
        prepare(list(ev.iter_history(text)), elide_trivial=True)
    )


def _cfg(tmp_path, **overrides) -> VerifydConfig:
    kw = dict(
        socket_path=str(tmp_path / "verifyd.sock"),
        workers=1,
        device="off",
        time_budget_s=10.0,
        no_viz=True,
        out_dir=str(tmp_path / "viz"),
        stats_log=str(tmp_path / "stats.jsonl"),
        state_dir=str(tmp_path / "state"),
        quarantine_threshold=3,
    )
    kw.update(overrides)
    return VerifydConfig(**kw)


def _events(tmp_path) -> list[dict]:
    with open(tmp_path / "stats.jsonl", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _crash(daemon: Verifyd) -> None:
    """Tear a constructed-but-never-entered daemon down the way SIGKILL
    would leave it: durable files closed mid-promise, no done records,
    no graceful drain."""
    daemon.journal.close()
    daemon.cache.close()
    if daemon.flight is not None:
        daemon.flight.close()
    if daemon.archive is not None:
        daemon.archive.close()
    if daemon._stats_file is not None:
        with contextlib.suppress(OSError):
            daemon._stats_file.close()


# -- the store itself --------------------------------------------------------


def test_store_counts_persist_and_release(tmp_path):
    s = QuarantineStore(str(tmp_path / "q"), threshold=2)
    fp = "ab" * 32
    assert s.note_crash(fp) == 1
    assert not s.is_quarantined(fp)

    again = QuarantineStore(str(tmp_path / "q"), threshold=2)  # "reboot"
    assert again.crash_count(fp) == 1  # the ledger survived
    assert again.note_crash(fp) == 2
    assert again.is_quarantined(fp)
    entry = again.get(fp)
    assert entry["fingerprint"] == fp and entry["crashes"] == 2

    assert again.release(fp) is True
    assert not again.is_quarantined(fp)
    assert again.release(fp) is False  # idempotent: nothing held

    # A conclusive verdict forgives accumulated warm counts.
    s2 = QuarantineStore(str(tmp_path / "q2"), threshold=3)
    s2.note_crash(fp)
    s2.note_crash(fp)
    s2.note_success(fp)
    assert s2.crash_count(fp) == 0


# -- the crash-loop scenario across boots ------------------------------------


def test_poison_quarantined_within_three_boots_innocent_replays(tmp_path):
    """A fingerprint in flight at three successive daemon deaths is
    quarantined; an unrelated orphan sharing the journal still replays
    and completes; release re-admits the poison fingerprint."""
    poison_text = good_history(1000)
    innocent_text = good_history(2000)
    poison_fp = _fingerprint(poison_text)
    innocent_fp = _fingerprint(innocent_text)
    cfg = _cfg(tmp_path)

    # Boot 1 dies mid-job: write the journal the way a killed daemon
    # leaves it — poison accepted AND started, innocent only accepted.
    journal = JobJournal(str(tmp_path / "state" / "journal"))
    journal.accept(
        job=1, fingerprint=poison_fp, client="poison", priority=10,
        history=poison_text,
    )
    journal.started(job=1, fingerprint=poison_fp)
    journal.accept(
        job=2, fingerprint=innocent_fp, client="innocent", priority=10,
        history=innocent_text,
    )
    journal.close()

    # Boots 2 and 3: recovery re-admits both orphans and charges the
    # started one a crash; a worker picks the poison job up (run record)
    # and the daemon dies again before it can finish.
    for boot, expected_crashes in ((2, 1), (3, 2)):
        d = Verifyd(cfg)
        d._recover_orphans()
        assert d.quarantine.crash_count(poison_fp) == expected_crashes
        assert not d.quarantine.is_quarantined(poison_fp)
        # Both orphans were re-admitted — the innocent one is not
        # filtered, it simply never gets a run record.
        batch = d.queue.get_batch(batch_max=16, timeout=1.0)
        batch += d.queue.get_batch(batch_max=16, timeout=0.1)
        by_fp = {j.fingerprint: j for j in batch}
        assert set(by_fp) == {poison_fp, innocent_fp}, f"boot {boot}"
        d.journal.started(
            job=by_fp[poison_fp].id, fingerprint=poison_fp
        )
        _crash(d)

    # Boot 4: the third charged crash crosses the threshold.  The poison
    # fingerprint is quarantined instead of replayed; the innocent
    # orphan replays through a live worker and completes.
    with Verifyd(cfg) as d:
        assert d.quarantine.is_quarantined(poison_fp)
        assert d.quarantine.crash_count(poison_fp) == 3
        client = VerifydClient(cfg.socket_path, timeout=60)

        # The innocent orphan's verdict lands in the durable cache; the
        # original submitter's retry answers warm.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if d.stats.snapshot()["completed"] >= 1:
                break
            time.sleep(0.05)
        reply = client.submit(innocent_text, client="retry")
        assert reply["verdict"] == 0 and reply["cached"] is True

        # A fresh submit of the poison history is refused outright —
        # definite, so a router never fails it over to poison a peer.
        with pytest.raises(VerifydError) as ei:
            client.submit(poison_text, client="retry")
        assert ei.value.cls == "Quarantined"
        assert ei.value.extra.get("fingerprint") == poison_fp
        assert ei.value.extra.get("crashes") == 3

        # Operator loop: list -> release -> the job completes normally.
        listing = client.quarantine("list")
        assert listing["threshold"] == 3
        assert [e["fingerprint"] for e in listing["entries"]] == [poison_fp]
        inspect = client.quarantine("inspect", poison_fp)
        assert inspect["crashes"] == 3
        released = client.quarantine("release", poison_fp)
        assert released["released"] is True
        reply = client.submit(poison_text, client="retry")
        assert reply["verdict"] == 0
        # The conclusive verdict forgave the ledger entry for good.
        assert d.quarantine.crash_count(poison_fp) == 0
        assert d.registry.get("verifyd_quarantine_size").value() == 0

    events = _events(tmp_path)
    quarantined = [e for e in events if e["ev"] == "job_quarantined"]
    assert len(quarantined) == 1
    assert quarantined[0]["fingerprint"] == poison_fp
    assert quarantined[0]["crashes"] == 3
    skipped = [e for e in events if e["ev"] == "orphan_quarantined"]
    assert len(skipped) == 1 and skipped[0]["fingerprint"] == poison_fp
    # The alert engine's builtin rules page on the quarantine event.
    from s2_verification_tpu.obs.alerts import builtin_rules

    assert any(r.event == "job_quarantined" for r in builtin_rules())


def test_queued_only_orphan_is_never_charged(tmp_path):
    """An orphan with no run record — the daemon died before any worker
    touched it — accrues no crash count no matter how many boots it
    survives in the journal."""
    text = good_history(3000)
    fp = _fingerprint(text)
    cfg = _cfg(tmp_path, workers=1)

    journal = JobJournal(str(tmp_path / "state" / "journal"))
    journal.accept(
        job=1, fingerprint=fp, client="queued", priority=10, history=text
    )
    journal.close()

    for _ in range(4):  # well past the threshold of 3
        d = Verifyd(cfg)
        d._recover_orphans()
        assert d.quarantine.crash_count(fp) == 0
        _crash(d)

    with Verifyd(cfg) as d:
        assert not d.quarantine.is_quarantined(fp)
        client = VerifydClient(cfg.socket_path, timeout=60)
        assert client.submit(text, client="retry")["verdict"] == 0
