"""Obs v4 tests: the federated fleet metrics plane and the
restart-surviving sentinel baselines.

Covers the FleetScraper merge (closed ``node`` label, dead-node gaps,
fleet SLO rollup), the router httpd's ``/fleet/*`` surfaces, the ``tsq``
op end to end on a live daemon, PerfSentinel baseline seeding across a
simulated restart — the headline: a post-restart slowdown judged against
the PRE-restart baseline still fires ``perf_regression`` — and the
doctor's telemetry-history section read cold off a SIGKILLed daemon.
"""

import io
import json
import os
import signal
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from helpers import H, fold
from s2_verification_tpu import cli
from s2_verification_tpu.obs.federate import (
    FleetScraper,
    ScrapeTarget,
    parse_exposition,
)
from s2_verification_tpu.obs.flight import postmortem, render_postmortem
from s2_verification_tpu.obs.httpd import MetricsServer
from s2_verification_tpu.obs.metrics import MetricsRegistry
from s2_verification_tpu.obs.sentinel import (
    PerfSentinel,
    SentinelConfig,
    seed_from_telemetry,
)
from s2_verification_tpu.obs.tsdb import (
    TelemetryStore,
    default_dir,
    last_values,
)
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.utils import events as ev

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _good() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


def _node_registry(jobs=10, queue=2.0, healthy=1.0, version="0.2.0"):
    """A fake backend registry carrying the families the plane reads."""
    reg = MetricsRegistry()
    reg.counter("verifyd_jobs_completed_total", "done").inc(jobs)
    reg.gauge("verifyd_queue_depth", "depth").set(queue)
    reg.gauge("verifyd_slo_healthy", "ok").set(healthy)
    reg.gauge(
        "verifyd_slo_availability", "avail", labelnames=("window",)
    ).set(0.99, window="fast")
    reg.gauge(
        "verifyd_build_info",
        "identity",
        labelnames=("version", "backend", "python"),
    ).set(1.0, version=version, backend="off", python="3.10")
    return reg


def _stats_target(reg):
    return ScrapeTarget(stats_fn=lambda: {"metrics": reg.snapshot()})


def _scraper(targets, **kw):
    kw.setdefault("interval_s", 60.0)  # tests drive scrape_once() directly
    return FleetScraper(MetricsRegistry(), targets, **kw)


# -- merge: the closed node label --------------------------------------------


def test_merge_injects_node_label_over_every_sample():
    ra = _node_registry(jobs=10, version="0.2.0")
    rb = _node_registry(jobs=20, version="0.3.0")
    sc = _scraper({"a": _stats_target(ra), "b": _stats_target(rb)})
    assert sc.scrape_once() == {"a": True, "b": True}

    text = sc.render()
    assert 'verifyd_jobs_completed_total{node="a"} 10' in text
    assert 'verifyd_jobs_completed_total{node="b"} 20' in text
    # node is the FIRST label even on already-labeled series
    assert 'verifyd_slo_availability{node="a",window="fast"}' in text
    # one TYPE line per family, not one per node
    assert text.count("# TYPE verifyd_jobs_completed_total") == 1
    # every sample carries a node value drawn from the closed member set
    samples, _types, _helps = parse_exposition(text)
    assert {s[1]["node"] for s in samples} == {"a", "b"}

    # the merged view also lands on the scraper's own registry, which is
    # what the router's TelemetryStore records for durable fleet history
    own = sc.registry.render()
    assert 'verifyd_fleet_node_up{node="a"} 1' in own
    assert "verifyd_fleet_nodes 2" in own

    # build identity is captured per node for `route fleet`
    build = sc.build_info()
    assert build["a"]["version"] == "0.2.0"
    assert build["b"]["version"] == "0.3.0"


def test_dead_backend_is_a_gap_not_a_zero():
    ra = _node_registry()

    def boom():
        raise OSError("connection refused")

    sc = _scraper({"a": _stats_target(ra), "b": ScrapeTarget(stats_fn=boom)})
    assert sc.scrape_once() == {"a": True, "b": False}

    text = sc.render()
    # the dead node contributes NO samples for real families — a gap —
    # but the synthetic up family still reports every configured member
    assert 'node="b"' not in text.replace(
        'verifyd_fleet_node_up{node="b"} 0', ""
    )
    assert 'verifyd_fleet_node_up{node="a"} 1' in text
    assert 'verifyd_fleet_node_up{node="b"} 0' in text
    assert sc.registry.get("verifyd_fleet_scrape_errors_total").value(
        node="b"
    ) == 1.0


def test_http_scrape_with_stats_fallback():
    ra = _node_registry(jobs=7)
    srv = MetricsServer(ra, 0)
    try:
        sc = _scraper(
            {
                "web": ScrapeTarget(metrics_url=srv.url),
                "op": _stats_target(_node_registry(jobs=9)),
            }
        )
        assert sc.scrape_once() == {"web": True, "op": True}
        text = sc.render()
        assert 'verifyd_jobs_completed_total{node="web"} 7' in text
        assert 'verifyd_jobs_completed_total{node="op"} 9' in text
        snap = sc.payload()
        assert snap["nodes"]["web"]["source"] == "http"
        assert snap["nodes"]["op"]["source"] == "stats"
    finally:
        srv.close()


# -- fleet SLO rollup --------------------------------------------------------


def test_fleet_slo_rollup_extremes_and_gaps():
    clock = [1000.0]
    ra = _node_registry(healthy=1.0)
    rb = _node_registry(healthy=0.0)

    def boom():
        raise OSError("dead")

    sc = FleetScraper(
        MetricsRegistry(),
        {
            "a": _stats_target(ra),
            "b": _stats_target(rb),
            "c": ScrapeTarget(stats_fn=boom),
        },
        interval_s=60.0,
        time_fn=lambda: clock[0],
    )
    sc.scrape_once()
    rollup = sc.slo_rollup()
    assert rollup["fleet"]["members"] == 3
    assert rollup["fleet"]["up"] == 2
    assert rollup["fleet"]["healthy_nodes"] == 1
    assert rollup["fleet"]["healthy"] is False  # one live node unhealthy
    assert rollup["nodes"]["a"]["healthy"] is True
    assert rollup["nodes"]["b"]["healthy"] is False
    assert rollup["nodes"]["c"] == {"up": False, "last_error": "dead"}
    assert rollup["fleet"]["availability_min"] == pytest.approx(0.99)

    # time passing without scrapes turns live nodes stale: gaps, not zeros
    clock[0] += 10_000.0
    rollup = sc.slo_rollup()
    assert rollup["fleet"]["up"] == 0
    assert rollup["nodes"]["a"]["up"] is False
    assert "jobs_per_sec" not in rollup["nodes"]["a"]


# -- the /fleet/* surfaces ---------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.status, resp.read().decode("utf-8")


def test_fleet_endpoints_served_by_obs_httpd():
    sc = _scraper(
        {"a": _stats_target(_node_registry()), "b": _stats_target(_node_registry())}
    )
    sc.scrape_once()
    srv = MetricsServer(sc.registry, 0, federator=sc)
    try:
        base = f"http://{srv.host}:{srv.port}"
        status, text = _get(base + "/fleet/metrics")
        assert status == 200 and 'node="a"' in text and 'node="b"' in text
        status, text = _get(base + "/fleet/slo")
        assert status == 200
        assert json.loads(text)["fleet"]["members"] == 2
        status, text = _get(base + "/fleet/dashboard")
        assert status == 200 and "<svg" in text and "verifyd fleet" in text
        status, text = _get(base + "/fleet/dashboard.json")
        assert status == 200 and set(json.loads(text)["nodes"]) == {"a", "b"}
    finally:
        srv.close()


def test_fleet_endpoints_absent_without_federator():
    reg = MetricsRegistry()
    srv = MetricsServer(reg, 0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://{srv.host}:{srv.port}/fleet/metrics")
        assert ei.value.code == 404
    finally:
        srv.close()


# -- restart-surviving sentinel baselines ------------------------------------


def test_slowdown_across_restart_fires_perf_regression(tmp_path):
    """The satellite-1 headline: boot 1 learns a baseline and dies; boot 2
    seeds from the telemetry store, so a post-restart slowdown is judged
    against the PRE-restart baseline and pages — no cold-start amnesia."""
    tdir = str(tmp_path / "tel")
    clock = [1000.0]

    # boot 1: live traffic builds a ~20ms baseline, history records it
    reg1 = MetricsRegistry()
    s1 = PerfSentinel(SentinelConfig(), registry=reg1, time_fn=lambda: clock[0])
    for _ in range(12):
        clock[0] += 1.0
        assert s1.observe("64x5x8", 0.020, t=clock[0]) is None
    store = TelemetryStore(tdir, reg1, time_fn=lambda: clock[0])
    store.sample_once()
    store.close()  # boot 1 dies

    # boot 2: fresh registry + sentinel, baselines restored from disk
    reg2 = MetricsRegistry()
    s2 = PerfSentinel(SentinelConfig(), registry=reg2, time_fn=lambda: clock[0])
    _t, finals = last_values(tdir)
    assert seed_from_telemetry(s2, finals) == 1
    snap = s2.snapshot()["shapes"]["64x5x8"]
    assert snap["baseline_wall_s"] == pytest.approx(0.020)
    assert snap["samples"] > SentinelConfig().min_samples  # warm, not cold

    # 4x slowdown right after the restart: fires on the 3rd consecutive
    # out-of-band sample, exactly as it would have without the restart
    reports = []
    for _ in range(3):
        clock[0] += 1.0
        reports.append(s2.observe("64x5x8", 0.080, t=clock[0]))
    assert reports[0] is None and reports[1] is None
    assert reports[2] is not None and reports[2]["shape"] == "64x5x8"
    assert reports[2]["baseline_wall_s"] < 0.03  # judged vs boot-1 baseline

    # control: an UNSEEDED sentinel is cold and never fires on the same
    # three samples — this is precisely the amnesia seeding removes
    s3 = PerfSentinel(SentinelConfig(), registry=MetricsRegistry())
    assert all(
        s3.observe("64x5x8", 0.080, t=2000.0 + i) is None for i in range(3)
    )


def test_latched_shape_stays_latched_across_restart():
    values = {
        'verifyd_perf_baseline_wall_seconds{shape="8x3x4"}': 0.02,
        'verifyd_perf_regression_fired{shape="8x3x4"}': 1.0,
        'verifyd_perf_baseline_wall_seconds{shape="bad"}': 0.0,  # rejected
    }
    s = PerfSentinel(SentinelConfig(), registry=MetricsRegistry())
    assert seed_from_telemetry(s, values) == 1
    # still out of band after the restart: latched, must NOT re-page
    assert s.observe("8x3x4", 0.080, t=1.0) is None
    # recovery re-arms, a fresh sustained slowdown pages again
    assert s.observe("8x3x4", 0.020, t=2.0) is None
    fired = [s.observe("8x3x4", 0.080, t=3.0 + i) for i in range(3)]
    assert fired[2] is not None


def test_live_samples_outrank_history():
    s = PerfSentinel(SentinelConfig(), registry=MetricsRegistry())
    s.observe("s", 0.01, t=1.0)
    assert s.seed("s", 9.9) is False  # already observed live traffic
    assert s.snapshot()["shapes"]["s"]["baseline_wall_s"] == 0.01


# -- daemon integration: boot seeding + the tsq op ---------------------------


def test_daemon_boots_seed_sentinel_and_serve_tsq(tmp_path):
    state_dir = str(tmp_path / "state")
    # manufacture boot-1 history carrying a sentinel baseline
    reg = MetricsRegistry()
    reg.gauge(
        "verifyd_perf_baseline_wall_seconds", "b", labelnames=("shape",)
    ).set(0.5, shape="99x9x9")
    reg.gauge(
        "verifyd_perf_regression_fired", "f", labelnames=("shape",)
    ).set(0.0, shape="99x9x9")
    store = TelemetryStore(default_dir(state_dir), reg, time_fn=lambda: 50.0)
    store.sample_once()
    store.close()

    cfg = VerifydConfig(
        socket_path=str(tmp_path / "v.sock"),
        out_dir=str(tmp_path / "viz"),
        no_viz=True,
        stats_log=None,
        device="off",
        state_dir=state_dir,
        telemetry_sample_s=30.0,  # the op forces samples; no thread races
    )
    with Verifyd(cfg) as daemon:
        assert daemon.telemetry is not None
        # boot 2 seeded the sentinel from boot 1's history
        shapes = daemon.sentinel.snapshot()["shapes"]
        assert shapes["99x9x9"]["baseline_wall_s"] == 0.5
        # build identity is a registry fact on every daemon
        assert "verifyd_build_info{" in daemon.registry.render()

        client = VerifydClient(cfg.socket_path)
        assert client.submit(_good(), client="tsq")["verdict"] == 0
        # the stats op surfaces the store
        snap = client.stats()
        assert snap["telemetry"]["dir"] == default_dir(state_dir)
        # live tsq: the op samples first, so the reply always has points
        info = client.tsq(info=True)
        assert info["resolutions"]["raw"]["records"] >= 2  # boot-1 + live
        out = client.tsq(metric="verifyd_build_info")
        assert any(
            "verifyd_build_info" in key for key in out["series"]
        )
        # the seeded baseline flows into boot 2's own recorded history
        out = client.tsq(metric="verifyd_perf_baseline_wall_seconds")
        (key,) = [k for k in out["series"] if "99x9x9" in k]
        assert out["series"][key][-1][1] == 0.5
        with pytest.raises(VerifydError):
            client.tsq(res="2h")


def test_tsq_without_state_dir_is_a_clean_error(tmp_path):
    cfg = VerifydConfig(
        socket_path=str(tmp_path / "v.sock"),
        out_dir=str(tmp_path / "viz"),
        no_viz=True,
        stats_log=None,
        device="off",
    )
    with Verifyd(cfg):
        client = VerifydClient(cfg.socket_path)
        assert client.submit(_good(), client="x")["verdict"] == 0
        with pytest.raises(VerifydError, match="no telemetry store"):
            client.tsq(info=True)


# -- doctor: telemetry history off a SIGKILLed daemon ------------------------

_TELEMETRY_CRASH_DRIVER = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import logging; logging.disable(logging.CRITICAL)

from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.client import VerifydClient

state_dir, sock, hist_path = sys.argv[1], sys.argv[2], sys.argv[3]
hist = open(hist_path, encoding="utf-8").read()

cfg = VerifydConfig(socket_path=sock, state_dir=state_dir, device="off",
                    no_viz=True, stats_log=None, workers=1,
                    telemetry_sample_s=0.1,
                    out_dir=os.path.join(state_dir, "viz"))
daemon = Verifyd(cfg).__enter__()
client = VerifydClient(sock, timeout=120)
client.submit(hist, client="tel")
# the sentinel baseline from that job must land in at least one sample
while daemon.telemetry.registry.get(
    "verifyd_telemetry_points_total"
).value(res="raw") < 4:
    time.sleep(0.05)
print("READY", flush=True)
time.sleep(600)  # parent SIGKILLs us here
"""


def test_doctor_reads_telemetry_of_a_sigkilled_daemon(tmp_path, capsys):
    state_dir = str(tmp_path / "state")
    sock = str(tmp_path / "v.sock")
    hist_path = str(tmp_path / "hist.jsonl")
    with open(hist_path, "w", encoding="utf-8") as f:
        f.write(_good())
    driver = str(tmp_path / "driver.py")
    with open(driver, "w", encoding="utf-8") as f:
        f.write(_TELEMETRY_CRASH_DRIVER.format(repo=REPO))

    proc = subprocess.Popen(
        [sys.executable, driver, state_dir, sock, hist_path],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.strip() == "READY", f"driver died early: {line!r}"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # the JSON surface: flushed-per-append rings survived the SIGKILL
    pm = postmortem(state_dir)
    assert not pm["clean_shutdown"]
    tel = pm["telemetry"]
    assert tel is not None
    assert tel["resolutions"]["raw"]["records"] >= 4
    assert tel["resolutions"]["raw"]["recovery"]["bad_segments"] == 0
    # the sentinel baseline the NEXT boot would seed from is right there
    assert any(
        k.startswith("verifyd_perf_baseline_wall_seconds")
        for k in tel["final_values"]
    )

    report = render_postmortem(pm)
    assert "telemetry history" in report
    assert "sentinel baselines at death" in report

    rc = cli.main(["doctor", "--state-dir", state_dir])
    out = capsys.readouterr().out
    assert rc == 1
    assert "UNCLEAN DEATH" in out
    assert "telemetry history" in out

    # cold tsq over the dead state dir agrees with the post-mortem
    rc = cli.main(
        ["tsq", "--state-dir", state_dir, "--metric", "verifyd_queue_depth"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "verifyd_queue_depth" in out
