"""Differential test: the device Step kernel vs the python model.

Random states × every op of collected histories (all three workflows, with
fencing tokens, match-seq-num guards, and every failure class) must produce
identical successor sets.
"""

import random
import zlib

import jax
import numpy as np
import pytest

from s2_verification_tpu.checker.entries import prepare
from s2_verification_tpu.collector.collect import CollectConfig, collect_history
from s2_verification_tpu.collector.fake_s2 import FaultPlan
from s2_verification_tpu.models.encode import encode_history
from s2_verification_tpu.models.stream import StreamState, step
from s2_verification_tpu.ops.step_kernel import DeviceOps, DeviceState, step_kernel


def collected(workflow, seed=5):
    events = collect_history(
        CollectConfig(
            num_concurrent_clients=3,
            num_ops_per_client=25,
            workflow=workflow,
            seed=seed,
            indefinite_failure_backoff_s=0.0,
            faults=FaultPlan.chaos(intensity=0.3, max_latency=0.001),
        )
    )
    return prepare(events, elide_trivial=False)


def random_states(enc, rng, n):
    """Random device states biased toward values that appear in the history."""
    tails = [0] + [int(t) for t in enc.out_tail[:20]]
    hashes = [(0, 0)] + list(zip(enc.out_hash_hi[:20], enc.out_hash_lo[:20]))
    states = []
    for _ in range(n):
        tail = rng.choice(tails) if rng.random() < 0.7 else rng.randrange(2**32)
        hh, hl = (
            hashes[rng.randrange(len(hashes))]
            if rng.random() < 0.7
            else (rng.randrange(2**32), rng.randrange(2**32))
        )
        token = rng.randrange(0, len(enc.token_of_id) + 1)
        states.append((tail, int(hh), int(hl), token))
    return states


def py_state(enc, dev):
    tail, hh, hl, tok = dev
    return StreamState(
        tail=tail,
        stream_hash=(hh << 32) | hl,
        fencing_token=enc.token_of_id[tok] if tok < len(enc.token_of_id) else f"?{tok}",
    )


@pytest.mark.parametrize("workflow", ["regular", "match-seq-num", "fencing"])
def test_step_kernel_matches_python_model(workflow):
    hist = collected(workflow)
    enc = encode_history(hist)
    if enc.num_ops == 0:
        pytest.skip("history fully reduced by forced prefix")
    dev_ops = DeviceOps.from_encoded(enc)
    rng = random.Random(zlib.crc32(workflow.encode()))

    # Map encoded op rows back to the python Ops they came from.
    forced = set(enc.forced_prefix)
    kept = [op for op in hist.ops if op.index not in forced]
    assert len(kept) == enc.num_ops

    kernel = jax.jit(
        jax.vmap(
            jax.vmap(step_kernel, in_axes=(None, None, 0)),  # over states
            in_axes=(None, 0, None),  # over ops
        )
    )
    states = random_states(enc, rng, 40)
    dev_states = DeviceState(
        tail=np.array([s[0] for s in states], np.uint32),
        hash_hi=np.array([s[1] for s in states], np.uint32),
        hash_lo=np.array([s[2] for s in states], np.uint32),
        token=np.array([s[3] for s in states], np.int32),
    )
    op_ids = np.arange(enc.num_ops)
    sa, va, sb, vb = jax.block_until_ready(kernel(dev_ops, op_ids, dev_states))
    sa = DeviceState(*(np.asarray(x) for x in sa))
    sb = DeviceState(*(np.asarray(x) for x in sb))
    va, vb = np.asarray(va), np.asarray(vb)

    def token_name(tok: int):
        return enc.token_of_id[tok] if tok < len(enc.token_of_id) else f"?{tok}"

    checked = 0
    for j, op in enumerate(kept):
        for k, dev in enumerate(states):
            ps = py_state(enc, dev)
            want = step(ps, op.inp, op.out)
            got = []
            if bool(va[j, k]):
                got.append(
                    StreamState(
                        tail=int(sa.tail[j, k]),
                        stream_hash=(int(sa.hash_hi[j, k]) << 32) | int(sa.hash_lo[j, k]),
                        fencing_token=token_name(int(sa.token[j, k])),
                    )
                )
            if bool(vb[j, k]):
                got.append(
                    StreamState(
                        tail=int(sb.tail[j, k]),
                        stream_hash=(int(sb.hash_hi[j, k]) << 32) | int(sb.hash_lo[j, k]),
                        fencing_token=token_name(int(sb.token[j, k])),
                    )
                )
            # Order-insensitive compare; the model may fork {opt, state}.
            assert set(got) == set(want), (
                f"op {j} ({op.inp.input_type}) state {ps}: kernel={got} model={want}"
            )
            checked += 1
    assert checked >= 40 * len(kept)


def test_forced_prefix_reduces_sequential_prologue():
    # A purely sequential history reduces entirely to the initial state set.
    from helpers import H, fold

    h = H()
    h.append_ok(1, [1, 2], tail=2)
    h.read_ok(1, tail=2, stream_hash=fold([1, 2]))
    h.check_tail_ok(1, tail=2)
    hist = prepare(h.events)
    enc = encode_history(hist)
    assert enc.num_ops == 0
    assert len(enc.forced_prefix) == 3
    assert [s.tail for s in enc.init_states] == [2]


def test_forced_prefix_stops_at_concurrency():
    from helpers import H, fold
    from s2_verification_tpu.utils.events import AppendSuccess

    h = H()
    h.append_ok(1, [1], tail=1)  # sequential prologue
    a = h.call_append(1, [2])  # overlaps with b
    b = h.call_append(2, [3])
    h.finish(1, a, AppendSuccess(tail=2))
    h.finish(2, b, AppendSuccess(tail=3))
    hist = prepare(h.events)
    enc = encode_history(hist)
    assert len(enc.forced_prefix) == 1
    assert enc.num_ops == 2
    assert [s.tail for s in enc.init_states] == [1]
