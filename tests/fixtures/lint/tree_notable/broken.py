# expect-file: parse-error
def broken(:
    return None
