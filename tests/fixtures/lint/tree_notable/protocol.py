"""Fixture protocol.py with the wire table missing on purpose."""  # expect: protocol-no-table

OPS = ("ping", "submit")


def encode_frame(obj):
    return repr(obj)
