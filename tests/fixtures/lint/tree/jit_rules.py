"""Fixture jit-hygiene sites: trigger, suppression, clean counterpart."""

from functools import partial

import jax

from .introspect import observe_jit


def _kernel(x):
    return x


run_kernel = jax.jit(_kernel)  # expect: jit-unwrapped

silenced_kernel = jax.jit(_kernel)  # verifylint: disable=jit-unwrapped

wrapped_kernel = jax.jit(_kernel)
wrapped_kernel = observe_jit("fixture.wrapped")(wrapped_kernel)


@jax.jit
def decorated(x, n):  # expect: jit-unwrapped
    if n:  # expect: jit-traced-branch
        return x + 1
    return x


@observe_jit("fixture.select")
@partial(jax.jit, static_argnames=("mode",))
def select(x, mode):
    if mode:  # clean: static parameter, not traced
        return x * 2
    if x.shape[0] > 2:  # clean: shape reads are static
        return x
    return x


def loops():
    fns = []
    for _i in range(3):
        fns.append(jax.jit(_kernel))  # expect: jit-in-loop
    return fns


def bad_static():
    return jax.jit(
        _kernel,
        static_argnums=[0],  # expect: jit-unhashable-static
    )
