"""Fixture event consumers: fold idiom, _count shape, AlertRule literals."""


class AlertRule:
    def __init__(self, event=None, field=None):
        self.event = event
        self.field = field


def fold(ev):
    name = ev.get("ev") or ev.get("event")
    if name == "job_done":
        return ev.get("verdict")  # clean: emitted field
    if name == "job_failed":  # expect: event-never-emitted
        return ev.get("reason")
    if name == "cache_hit":
        return ev.get("latency_s")  # expect: event-field-unwritten
    if name == "open_evt":
        return ev.get("anything")  # clean: open event, lower-bound fields
    return None


def _count(event, fields):
    if event == "ghost_evt":  # expect: event-never-emitted
        return fields.get("x")
    if event == "job_done":
        return fields.get("wall_s")  # clean: emitted field
    return None


RULES = [
    AlertRule(event="job_done", field="verdict"),
    AlertRule(event="vanished", field="x"),  # expect: event-never-emitted
]
