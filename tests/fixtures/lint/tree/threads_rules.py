"""Fixture concurrency sites: racy write, locked/atomic clean shapes."""

import threading


class Racy:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while True:
            self.count += 1  # expect: concurrency-unlocked-write

    def snapshot(self):
        return self.count


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        with self._lock:
            self.count += 1  # clean: written under the instance lock

    def snapshot(self):
        with self._lock:
            return self.count


class Silenced:
    def __init__(self):
        self.flag = 0

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self.flag += 1  # verifylint: disable=concurrency-unlocked-write

    def read(self):
        return self.flag


class Publisher:
    def __init__(self):
        self._stop = None

    def start(self):
        self._stop = threading.Event()  # clean: one-shot atomic publication
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while not self._stop.is_set():
            pass
