"""Fixture parse sites, one per protocol read rule."""


def handle(req):
    history = req["history"]  # clean: required field, always present
    compression = req.get("zcomp")  # expect: protocol-unknown-field
    deadline = req["deadline"]  # expect: protocol-unguarded-read
    client = req.get("client", "?")
    guarded = None
    if req.get("deadline") is not None:
        guarded = req["deadline"]  # clean: guarded by req.get()
    return history, compression, deadline, client, guarded
