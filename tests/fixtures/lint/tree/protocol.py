"""Fixture wire table: _frame_mac disagrees with UNSIGNED_FIELDS."""  # expect: protocol-unsigned-mismatch

FRAME_FIELDS = {
    "ping": {},
    "submit": {
        "history": "required",
        "client": "optional",
        "deadline": "optional",
    },
}
UNSIGNED_FIELDS = ("auth",)


def _frame_mac(obj):
    # Excludes "mac", but UNSIGNED_FIELDS declares "auth": fields silently
    # escape (or double-enter) the authenticated region.
    body = {k: v for k, v in obj.items() if k != "mac"}
    return repr(sorted(body.items()))
