"""Fixture metrics-cardinality sites: naming and label closedness."""

VERDICT_LABELS = {"ok": "pass", "bad": "fail"}


def register(reg):
    good_counter = reg.counter("verifyd_jobs_total")
    good_gauge = reg.gauge("verifyd_queue_depth")
    good_hist = reg.histogram("verifyd_wall_seconds")
    bad_prefix = reg.counter("jobs_total")  # expect: metric-name
    bad_counter = reg.counter("verifyd_jobs")  # expect: metric-name
    bad_hist = reg.histogram("verifyd_wall")  # expect: metric-name
    return good_counter, good_gauge, good_hist, bad_prefix, bad_counter, bad_hist


def record(m, fingerprint):
    m.inc(backend="native")  # clean: literal
    m.inc(backend=fingerprint)  # expect: metric-open-label
    m.inc(shard=fingerprint)  # verifylint: disable=metric-open-label
    backend = str(fingerprint)
    if backend not in ("native", "oracle"):
        backend = "other"
    m.inc(backend=backend)  # clean: validated enum fold
    for writer in ("flight", "archive"):
        m.inc(writer=writer)  # clean: loop over literal tuple
    m.inc(verdict=VERDICT_LABELS.get(fingerprint, "other"))  # clean: dict fold
    m.observe(0.5, exemplar=fingerprint)  # clean: exemplars are exempt
