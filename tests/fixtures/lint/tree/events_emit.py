"""Fixture event emitters: two closed events and one open event."""


def produce(stats):
    stats.emit("job_done", verdict="ok", wall_s=1.0)
    stats.emit("cache_hit", job="j1")
    stats.emit("open_evt", a=1, **{"b": 2})
