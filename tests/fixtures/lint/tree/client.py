"""Fixture construction sites, one per protocol construction rule."""


def build_ping():
    return {"op": "ping"}


def build_bad_op():
    return {"op": "snapshot"}  # expect: protocol-unknown-op


def build_unknown_field():
    return {
        "op": "submit",
        "history": [],
        "compression": "zstd",  # expect: protocol-unknown-field
    }


def build_missing_required():
    return {"op": "submit", "client": "c1"}  # expect: protocol-missing-required


def build_missing_required_suppressed():
    return {"op": "submit"}  # verifylint: disable=protocol-missing-required


def build_required_via_store():
    req = {"op": "submit", "client": "c2"}
    req["history"] = []
    return req
