"""Flight recorder + doctor tests: the bounded on-disk ring, synthetic
post-mortems, clean-shutdown detection on a real daemon, and the headline
scenario — a SIGKILLed daemon whose state dir the doctor reads cold
(flight tail including the SLO breach, plus the orphaned journal entry).
"""

import io
import json
import os
import signal
import subprocess
import sys
import time

from helpers import H, fold
from s2_verification_tpu import cli
from s2_verification_tpu.obs.flight import (
    FLIGHT_SUBDIR,
    FlightRecorder,
    postmortem,
    read_flight,
    render_postmortem,
)
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.client import VerifydClient
from s2_verification_tpu.utils import events as ev

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flight_dir(state_dir):
    return os.path.join(str(state_dir), FLIGHT_SUBDIR)


def _good() -> str:
    h = H()
    h.append_ok(1, [111], tail=1)
    h.read_ok(2, tail=1, stream_hash=fold([111]))
    buf = io.StringIO()
    ev.write_history(h.events, buf)
    return buf.getvalue()


# -- recorder ----------------------------------------------------------------


def test_flight_ring_is_bounded_and_replayable(tmp_path):
    rec = FlightRecorder(
        _flight_dir(tmp_path), max_segment_bytes=512, max_segments=2
    )
    for i in range(500):
        rec.record_event({"ev": "done", "t": 1000.0 + i, "job": i})
    rec.dump("shutdown")
    rec.close()
    records = read_flight(str(tmp_path))
    # Drop-oldest: the ring kept a bounded tail that ends with the dump.
    assert 0 < len(records) < 500
    assert records[-1] == {
        "k": "dump",
        "t": records[-1]["t"],
        "reason": "shutdown",
    }
    jobs = [r["job"] for r in records if r["k"] == "ev"]
    assert jobs == sorted(jobs) and jobs[-1] == 499  # newest survives


def test_recorder_ignores_non_x_spans_and_survives_close(tmp_path):
    rec = FlightRecorder(_flight_dir(tmp_path))
    rec.record_span({"ph": "M", "name": "thread_name"})  # metadata: skipped
    rec.record_span({"ph": "X", "name": "s", "ts": 1.0, "dur": 2.0, "tid": 3})
    rec.close()
    rec.record_event({"ev": "late"})  # after close: silently dropped
    records = read_flight(str(tmp_path))
    assert [r["k"] for r in records] == ["span"]
    assert records[0]["name"] == "s"


def test_read_flight_tolerates_missing_ring(tmp_path):
    assert read_flight(str(tmp_path / "never-existed")) == []


# -- synthetic post-mortem ---------------------------------------------------


def test_postmortem_reconstructs_breach_leases_and_unclean_death(tmp_path):
    rec = FlightRecorder(_flight_dir(tmp_path))
    # Timestamps must be wall-adjacent: dump/span records stamp real wall
    # time, and the replay evaluates windows at the LAST recorded instant.
    t = time.time() - 30.0
    rec.record_event({"ev": "lease_grant", "t": t, "job": 5, "devices": [0, 1]})
    rec.record_event({"ev": "lease_grant", "t": t + 1, "job": 6, "devices": [2]})
    rec.record_event({"ev": "lease_release", "t": t + 2, "job": 5})
    for i in range(12):
        rec.record_event({"ev": "job_error", "t": t + 3 + i, "job": 10 + i})
    rec.record_span(
        {"ph": "X", "name": "search", "ts": 0.0, "dur": 9e6, "tid": 5}
    )
    rec.dump(
        "slo_breach",
        breach={"reasons": [{"kind": "fast_burn", "burn_rate": 100.0,
                             "window": "1m"}]},
    )
    # No shutdown dump: the daemon died mid-flight.
    rec.close()

    pm = postmortem(str(tmp_path))
    assert not pm["clean_shutdown"]
    assert len(pm["breaches"]) == 1
    # job 6's grant was never released → open at death.
    assert [l["job"] for l in pm["open_leases"]] == [6]
    assert pm["slowest_spans"][0]["name"] == "search"
    # SLO replayed from the recorded events at the moment of death.
    assert not pm["slo_at_death"]["healthy"]

    report = render_postmortem(pm)
    assert "UNCLEAN DEATH" in report
    assert "SLO breaches recorded" in report
    assert "fast_burn" in report
    assert "leases open at death: 1" in report
    assert "flight tail" in report


def test_postmortem_on_clean_daemon_shutdown(tmp_path):
    cfg = VerifydConfig(
        socket_path=str(tmp_path / "v.sock"),
        out_dir=str(tmp_path / "viz"),
        no_viz=True,
        stats_log=None,
        device="off",
        state_dir=str(tmp_path / "state"),
    )
    with Verifyd(cfg) as daemon:
        client = VerifydClient(cfg.socket_path)
        assert client.submit(_good(), client="doc")["verdict"] == 0
    pm = postmortem(cfg.state_dir)
    assert pm["clean_shutdown"]
    assert pm["last_record"]["reason"] == "shutdown"
    # The shutdown dump carries the SLO snapshot at that instant.
    assert "slo" in pm["last_record"]
    assert pm["events"] > 0 and pm["spans"] > 0
    assert "clean shutdown" in render_postmortem(pm)


# -- the headline: doctor on a SIGKILLed daemon ------------------------------

_CRASH_DRIVER = """
import os, sys, threading, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from s2_verification_tpu.service import scheduler as sched_mod
from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
from s2_verification_tpu.service.client import VerifydClient, VerifydError
from s2_verification_tpu.checker.oracle import CheckOutcome, CheckResult

state_dir, sock, hist_path = sys.argv[1], sys.argv[2], sys.argv[3]
hist = open(hist_path, encoding="utf-8").read()

calls = {{"n": 0}}
def stub(h, budget, profile=False):
    calls["n"] += 1
    if calls["n"] <= 12:
        raise RuntimeError("induced failure %d" % calls["n"])
    time.sleep(600)  # the 13th job hangs: accepted, never closed
sched_mod._cpu_check = stub

import logging; logging.disable(logging.CRITICAL)
cfg = VerifydConfig(socket_path=sock, state_dir=state_dir, device="off",
                    no_viz=True, stats_log=None, workers=1,
                    out_dir=os.path.join(state_dir, "viz"))
daemon = Verifyd(cfg).__enter__()
client = VerifydClient(sock, timeout=120)
for i in range(12):
    try:
        client.submit(hist, client="burst%d" % i)
    except VerifydError:
        pass
threading.Thread(
    target=lambda: client.submit(hist, client="hung"), daemon=True
).start()
while calls["n"] < 13:
    time.sleep(0.05)
print("READY", flush=True)
time.sleep(600)  # parent SIGKILLs us here
"""


def test_doctor_reads_a_sigkilled_daemons_state_dir(tmp_path, capsys):
    state_dir = str(tmp_path / "state")
    sock = str(tmp_path / "v.sock")
    hist_path = str(tmp_path / "hist.jsonl")
    with open(hist_path, "w", encoding="utf-8") as f:
        f.write(_good())
    driver = str(tmp_path / "driver.py")
    with open(driver, "w", encoding="utf-8") as f:
        f.write(_CRASH_DRIVER.format(repo=REPO))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, driver, state_dir, sock, hist_path],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.strip() == "READY", f"driver died early: {line!r}"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    rc = cli.main(["doctor", "--state-dir", state_dir])
    out = capsys.readouterr().out
    assert rc == 1  # unclean death is the scriptable verdict
    assert "UNCLEAN DEATH" in out
    assert "SLO breaches recorded" in out  # the burst tripped fast burn
    assert "orphaned journal entries" in out  # the hung 13th job
    assert "client=hung" in out
    assert "flight tail" in out

    # The JSON surface agrees with the rendered one.
    pm = postmortem(state_dir)
    assert not pm["clean_shutdown"]
    assert pm["breaches"]
    assert any(o.get("client") == "hung" for o in pm["orphans"])
    assert not pm["slo_at_death"]["healthy"]


def test_doctor_on_missing_state_dir_is_a_usage_error(tmp_path):
    rc = cli.main(["doctor", "--state-dir", str(tmp_path / "nope")])
    assert rc == 64  # EX_USAGE
