"""Crash-resilience of long device runs (checker/resilient.py).

The axon TPU worker dies outright on HBM exhaustion and a dead tunnel
hangs backend init; the resilient driver must turn both into "one lost
segment + auto-resume".  Unit tests drive fake children through the
crash/hang/success shapes; the integration test kills a real adv_bench
device search (SIGKILL, no cleanup — a faithful worker death) at its
first checkpoint and requires the relaunch to resume from that
checkpoint to a conclusive verdict.  No reference analog: the CPU
engine there cannot take its own machine down.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

from s2_verification_tpu.checker.resilient import DriveOutcome, drive

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(tmp_path, body: str) -> list[str]:
    p = tmp_path / "child.py"
    p.write_text(textwrap.dedent(body))
    return [sys.executable, str(p)]


def test_drive_crash_once_then_resume(tmp_path):
    """First attempt dies before writing the result; second concludes."""
    marker = tmp_path / "progress"
    result = tmp_path / "result"
    cmd = _script(
        tmp_path,
        f"""
        import os, signal
        if not os.path.exists({str(marker)!r}):
            open({str(marker)!r}, "w").close()   # "checkpoint"
            os.kill(os.getpid(), signal.SIGKILL)
        open({str(result)!r}, "w").close()
        """,
    )
    out = drive(cmd, done=result.exists, attempt_timeout_s=60, probe_cmd=None)
    assert out == DriveOutcome(True, 2, 0, "conclusive")


def test_drive_hang_is_killed_then_resume(tmp_path):
    """A mid-run hang (tunnel wedge) is bounded by the attempt timeout."""
    marker = tmp_path / "progress"
    result = tmp_path / "result"
    cmd = _script(
        tmp_path,
        f"""
        import os, time
        if not os.path.exists({str(marker)!r}):
            open({str(marker)!r}, "w").close()
            time.sleep(3600)
        open({str(result)!r}, "w").close()
        """,
    )
    # Attempt timeout: big enough that interpreter startup on a loaded
    # machine can't kill the child BEFORE its marker (which would replay
    # the hang forever), small enough not to dominate suite wall time —
    # the first attempt always sleeps until this timeout kills it.
    out = drive(cmd, done=result.exists, attempt_timeout_s=10, probe_cmd=None)
    assert out.ok and out.attempts == 2 and out.last_rc == 0


def test_drive_restart_budget_exhausted(tmp_path):
    """A child that always dies fails conclusively, never loops forever."""
    result = tmp_path / "result"
    cmd = _script(tmp_path, "raise SystemExit(3)")
    out = drive(
        cmd, done=result.exists, attempt_timeout_s=60, max_restarts=2, probe_cmd=None
    )
    assert not out.ok and out.attempts == 3 and out.last_rc == 3
    assert out.note == "restart budget exhausted"


def test_drive_zero_exit_without_result_is_a_failed_attempt(tmp_path):
    """rc 0 is not success — only done() is (a child can die orderly
    after losing its device but before writing the result)."""
    result = tmp_path / "result"
    cmd = _script(tmp_path, "pass")
    out = drive(
        cmd, done=result.exists, attempt_timeout_s=60, max_restarts=1, probe_cmd=None
    )
    assert not out.ok and out.attempts == 2


def test_drive_probe_gates_relaunch(tmp_path):
    """Between attempts the backend probe must answer before relaunch;
    a probe that never answers fails the drive with its own note."""
    result = tmp_path / "result"
    cmd = _script(tmp_path, "raise SystemExit(1)")
    out = drive(
        cmd,
        done=result.exists,
        attempt_timeout_s=60,
        max_restarts=3,
        probe_cmd=[sys.executable, "-c", "raise SystemExit(1)"],
        probe_interval_s=0.01,
        max_probes=2,
    )
    assert not out.ok and out.attempts == 1
    assert out.note == "backend never answered between attempts"


def test_default_probe_cmd_gates_and_passes_on_pinned_cpu(tmp_path, monkeypatch):
    """The REAL probe command (the one the on-chip driver uses between
    attempts) must succeed under an explicit JAX_PLATFORMS=cpu pin — the
    config-API re-pin inside it is what defeats the axon sitecustomize
    override — so a relaunch is gated on a live backend, not a fake."""
    from s2_verification_tpu.checker.resilient import default_probe_cmd

    marker = tmp_path / "progress"
    result = tmp_path / "result"
    cmd = _script(
        tmp_path,
        f"""
        import os, signal
        if not os.path.exists({str(marker)!r}):
            open({str(marker)!r}, "w").close()
            os.kill(os.getpid(), signal.SIGKILL)
        open({str(result)!r}, "w").close()
        """,
    )
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    out = drive(
        cmd,
        done=result.exists,
        attempt_timeout_s=60,
        probe_cmd=default_probe_cmd(),
        probe_timeout_s=120,
        probe_interval_s=0.01,
        max_probes=2,
    )
    assert out == DriveOutcome(True, 2, 0, "conclusive")


def test_adv_bench_resilient_resumes_through_worker_death(tmp_path):
    """End to end: the device search is SIGKILLed at its first checkpoint
    (S2VTPU_TEST_CRASH_ON_CHECKPOINT=1), and the resilient parent resumes
    it from that checkpoint to a conclusive OK in exactly two attempts."""
    env = dict(os.environ)
    env["S2VTPU_TEST_CRASH_ON_CHECKPOINT"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    ck = tmp_path / "ck" / "adv"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "adv_bench.py"),
            "7",
            "--skip-oracle",
            "--skip-native",
            "--resilient",
            "--no-probe",
            "--once",
            "--checkpoint-every",
            "2",
            "--checkpoint",
            str(ck),
            "--frontier",
            "65536",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [l for l in proc.stdout.splitlines() if l.startswith("resilient k=7")]
    assert line and "OK" in line[0] and "attempts=2" in line[0], proc.stdout
    res = json.loads((tmp_path / "ck" / "adv.k7.json").read_text())
    assert res["outcome"] == "OK" and res["k"] == 7
    # The conclusive run cleaned its checkpoint up.
    assert not (tmp_path / "ck" / "adv.k7").exists()
