"""Profile archive: durable round-trips, query semantics, replay parity.

The archive is the daemon's flight-data recorder for *performance*: one
compact record per finished job plus the history corpus keyed by
fingerprint, both over CRC-checked segment logs.  These tests cover the
unit fold (observe_event → record, lease-wait correlation), the cold
readers, the filter algebra, and the end-to-end contract the ISSUE
names: after a daemon dies, ``profiles`` still lists its jobs and
``scripts/workload_replay.py`` re-runs them with verdict parity.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from s2_verification_tpu.obs.archive import (
    ProfileArchive,
    filter_records,
    read_archive,
    read_corpus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _done(job, **kw):
    ev = {
        "ev": "done",
        "t": 100.0 + job,
        "job": job,
        "client": "t",
        "shape": "4x2x8",
        "backend": "native",
        "verdict": 0,
        "wall_s": 0.01 * job,
        "queue_wait_s": 0.001,
        "ops": 8,
        "fingerprint": f"v1:{job:016x}:8",
        "profile": {"layers": 3},
    }
    ev.update(kw)
    return ev


# -- unit: the fold and the cold readers ------------------------------------


def test_archive_round_trip(tmp_path):
    d = str(tmp_path / "profiles")
    a = ProfileArchive(d)
    a.observe_event({"ev": "lease_grant", "job": 2, "wait_s": 0.25})
    a.observe_event(_done(1))
    a.observe_event(_done(2, verdict=1, backend="device-mesh[4]"))
    a.observe_event({"ev": "accept", "job": 3})  # not a done: ignored
    assert a.add_history("v1:%016x:8" % 1, "line1\n")
    assert not a.add_history("v1:%016x:8" % 1, "line1\n")  # dedup by fp
    assert len(a) == 2
    a.close()

    b = ProfileArchive(d)
    assert len(b) == 2
    recs = b.query()
    assert [r["job"] for r in recs] == [1, 2]
    assert recs[0]["fp"] == "v1:%016x:8" % 1
    assert recs[0]["profile"] == {"layers": 3}
    # lease_grant wait correlated onto job 2's record only
    assert "lease_wait_s" not in recs[0]
    assert recs[1]["lease_wait_s"] == 0.25
    assert b.history("v1:%016x:8" % 1) == "line1\n"
    b.close()


def test_cold_readers_tolerate_missing_state(tmp_path):
    assert read_archive(str(tmp_path)) == []
    assert read_corpus(str(tmp_path)) == {}


def test_cold_readers_see_unclosed_appends(tmp_path):
    state = str(tmp_path)
    a = ProfileArchive(os.path.join(state, "profiles"))
    a.observe_event(_done(1))
    a.add_history("v1:%016x:8" % 1, "line1\n")
    a.close()
    recs = read_archive(state)
    assert len(recs) == 1 and recs[0]["job"] == 1
    assert read_corpus(state) == {"v1:%016x:8" % 1: "line1\n"}


def test_filter_records_algebra():
    recs = [
        _done(1),
        _done(2, shape="8x4x16", wall_s=5.0),
        _done(3, verdict=2, backend="device-mesh[2]", client="u"),
        _done(4, t=500.0),
    ]
    for r in recs:
        r["fp"] = r.pop("fingerprint")
    assert [r["job"] for r in filter_records(recs, shape="8x4x16")] == [2]
    assert [r["job"] for r in filter_records(recs, backend="device")] == [3]
    assert [r["job"] for r in filter_records(recs, verdict=2)] == [3]
    assert [r["job"] for r in filter_records(recs, client="u")] == [3]
    assert [r["job"] for r in filter_records(recs, since=200.0)] == [4]
    # slowest ranks by wall desc and wins over limit
    slow = filter_records(recs, slowest=2, limit=1)
    assert [r["job"] for r in slow] == [2, 4]
    # limit keeps the newest N in recorded order
    assert [r["job"] for r in filter_records(recs, limit=2)] == [3, 4]
    # returned records are copies, not aliases
    filter_records(recs)[0]["job"] = 999
    assert recs[0]["job"] == 1


# -- end to end: archive a workload, kill the daemon, query + replay --------


@pytest.fixture(scope="module")
def archived_state(tmp_path_factory):
    """A state dir left behind by a daemon that verified three histories."""
    from s2_verification_tpu.collector.collect import (
        CollectConfig,
        collect_history,
    )
    from s2_verification_tpu.service.client import VerifydClient
    from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig
    from s2_verification_tpu.utils import events as ev

    tmp = tmp_path_factory.mktemp("archive-e2e")
    state = str(tmp / "state")
    sock = str(tmp / "verifyd.sock")
    texts = []
    for seed in range(3):
        hist = collect_history(
            CollectConfig(
                num_concurrent_clients=2, num_ops_per_client=8, seed=seed
            )
        )
        buf = io.StringIO()
        ev.write_history(hist, buf)
        texts.append(buf.getvalue())

    cfg = VerifydConfig(
        socket_path=sock,
        state_dir=state,
        device="off",
        no_viz=True,
        stats_log=None,
        out_dir=str(tmp / "viz"),
    )
    verdicts = []
    with Verifyd(cfg):
        client = VerifydClient(sock)
        for text in texts:
            reply = client.submit(text, client="e2e")
            verdicts.append(reply["verdict"])
    return {"state": state, "sock": sock, "cfg": cfg, "verdicts": verdicts}


def test_profiles_survive_restart(archived_state):
    from s2_verification_tpu.service.client import VerifydClient
    from s2_verification_tpu.service.daemon import Verifyd

    # Cold: straight off the segment logs, no daemon.
    cold = read_archive(archived_state["state"])
    assert len(cold) == 3
    corpus = read_corpus(archived_state["state"])
    assert set(corpus) == {r["fp"] for r in cold}
    for rec in cold:
        assert rec["shape"] and rec["wall_s"] is not None
        assert rec.get("profile") is None or isinstance(rec["profile"], dict)

    # Warm: a restarted daemon replays the archive and answers the op.
    with Verifyd(archived_state["cfg"]):
        client = VerifydClient(archived_state["sock"])
        reply = client.profiles()
        assert reply["total"] == 3
        assert len(reply["records"]) == 3
        one = client.profiles(slowest=1)
        assert len(one["records"]) == 1
        assert one["records"][0]["wall_s"] == max(r["wall_s"] for r in cold)


def test_profiles_op_without_state_dir_is_decode_error(tmp_path):
    from s2_verification_tpu.service.client import VerifydClient, VerifydError
    from s2_verification_tpu.service.daemon import Verifyd, VerifydConfig

    sock = str(tmp_path / "verifyd.sock")
    cfg = VerifydConfig(
        socket_path=sock, device="off", no_viz=True, stats_log=None
    )
    with Verifyd(cfg):
        client = VerifydClient(sock)
        with pytest.raises(VerifydError) as ei:
            client.profiles()
        assert ei.value.cls == "DecodeError"


@pytest.mark.slow
def test_workload_replay_parity(archived_state):
    """scripts/workload_replay.py re-runs the archived jobs against a
    fresh daemon and exits 0 with zero verdict mismatches."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "workload_replay.py"),
            "--state-dir",
            archived_state["state"],
            "--concurrency",
            "2",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "replay_jobs_per_sec"
    assert line["jobs"] == 3
    assert line["mismatches"] == 0
    assert line["skipped"] == 0
    assert line["recorded_avg_wall_s"] > 0


def test_archive_in_stats_snapshot(archived_state):
    from s2_verification_tpu.service.client import VerifydClient
    from s2_verification_tpu.service.daemon import Verifyd

    with Verifyd(archived_state["cfg"]):
        client = VerifydClient(archived_state["sock"])
        snap = client.stats()
        assert snap["archive"]["records"] == 3
        assert snap["archive"]["histories"] == 3
