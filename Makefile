# Build/test entry points, mirroring the reference's Makefile
# (reference Makefile:1-24: build-go/test-go/install-go/clean-go).

PYTHON ?= python

.PHONY: all native test test-fast fuzz bench clean

all: native

# The native C++ checker (the reference's compiled-Go/porcupine analog).
native:
	$(MAKE) -C native

fuzz: native  ## deep cross-engine differential soak (set TRIALS=N, default 300)
	S2VTPU_FUZZ_TRIALS=$(or $(TRIALS),300) $(PYTHON) -m pytest tests/test_fuzz_differential.py -q

test: native
	$(PYTHON) -m pytest tests/ -q

# Skip the slow device differential sweeps.
test-fast: native
	$(PYTHON) -m pytest tests/ -q -k "not device and not dryrun"

bench:
	$(PYTHON) bench.py

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache s2_verification_tpu/__pycache__
