# Build/test entry points, mirroring the reference's Makefile
# (reference Makefile:1-24: build-go/test-go/install-go/clean-go).

PYTHON ?= python

.PHONY: all native test test-fast t1 fuzz bench chaos chaos-full obs mesh fleet distsearch telemetry overload soak batch prefix prune perfgate lint clean

all: native

# The native C++ checker (the reference's compiled-Go/porcupine analog).
native:
	$(MAKE) -C native

fuzz: native  ## deep cross-engine differential soak (set TRIALS=N, default 300)
	S2VTPU_FUZZ_TRIALS=$(or $(TRIALS),300) $(PYTHON) -m pytest tests/test_fuzz_differential.py -q

# Marker-based selection (the tier-1 discipline): tests opt out via
# @pytest.mark.slow instead of maintaining a -k name blocklist, and a
# module that fails to import is reported rather than aborting the run.
test: native lint
	$(PYTHON) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors

test-fast: native
	$(PYTHON) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors

# The ROADMAP tier-1 gate, verbatim (scripts/t1.sh).
t1:
	bash scripts/t1.sh

bench:
	$(PYTHON) bench.py

# Durability/transport chaos harness (scripts/chaos_bench.py): fault
# proxy + auth probes + SIGKILL crash recovery.  `chaos` is the short
# smoke; `chaos-full` runs the whole fault matrix (the slow-marked
# pytest path runs the smoke too: tests/test_chaos.py).
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/chaos_bench.py --quick

chaos-full: lint obs mesh fleet distsearch telemetry overload soak batch prefix prune
	JAX_PLATFORMS=cpu $(PYTHON) scripts/chaos_bench.py

# Observability smoke (scripts/obs_check.py): boot verifyd with
# --metrics-port + tracing + per-job profiling, drive a short load,
# assert the /metrics exposition (required families, histogram
# integrity), the stats-op merge, and the Perfetto-loadable trace.
obs:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/obs_check.py

# Perf-regression gate (scripts/perf_watch.py): per-shape p95 EWMA drift
# over service_bench history, the offline counterpart of the in-daemon
# sentinel.  The selftest proves the gate end-to-end — a synthetically
# slowed shape_key must exit nonzero, an in-band run must pass.
perfgate:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/perf_watch.py --selftest

# Multi-chip serving gate (scripts/mesh_check.py): 8 virtual CPU devices,
# verifyd --mesh-devices 8 vs 1, same adversarial history through the
# supervised sharded escalation path — verdicts must agree and the
# per-shard metric families must populate.
mesh:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/mesh_check.py

# Overload-protection gate (scripts/overload_check.py): poison-job
# quarantine within 3 SIGKILL boots with zero impact on an innocent
# journal-mate, a 2s deadline freeing worker+child+lease within
# deadline+grace, injected ENOSPC degrading to explicit non-durable
# mode without dropping in-flight jobs, and the armed
# AdmissionController within 3% of a disarmed service_bench run.
overload:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/overload_check.py

# Closed-loop soak gate (scripts/soak_check.py): the full seeded
# campaign matrix (every violation class once) through a router +
# 2-daemon fleet with one backend SIGKILLed and restarted mid-soak —
# every ground-truth label must match its verdict with zero lost jobs,
# and a deliberately mislabeled control must fire the
# checker_false_verdict alert, dump a flight marker, and exit nonzero.
soak:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/soak_check.py

# Static-analysis gate (verifylint, s2_verification_tpu/analysis/):
# five domain-aware passes over the whole package — jit-hygiene,
# event-schema, metrics-cardinality, concurrency, protocol-compat.
# Exits nonzero on any error not in .verifylint-baseline.json and when
# docs/EVENTS.md drifts from the event registry.
lint:
	JAX_PLATFORMS=cpu $(PYTHON) -m s2_verification_tpu.cli lint
	JAX_PLATFORMS=cpu $(PYTHON) -m s2_verification_tpu.cli lint --check-events-md

# Continuous-batching gate (scripts/batch_check.py): a live --batching
# daemon under mixed-shape concurrent load — verdict parity with
# one-shot check on every reply, zero lost jobs, throughput over the
# published single-daemon baseline, multi-lane batch_launch events with
# per-job done attribution intact.
batch: native
	JAX_PLATFORMS=cpu $(PYTHON) scripts/batch_check.py

# Incremental-verification gate (scripts/prefix_check.py): a live
# --prefix daemon SIGKILLed mid-follow reboots on the same --state-dir
# with the frontier intact and resumes warm; warm re-verification after
# a 10% append must finish within 25% of the cold wall with the
# identical verdict; campaign parity against a prefix-less daemon.
prefix:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/prefix_check.py

# Search-pruning gate (scripts/prune_check.py): verdict parity of the
# pruned + speculative engines (host frontier, native DFS, device
# search) against the un-pruned referee across the full builtin
# campaign matrix and all four violation classes, plus a >=1.3x
# wall-time gate on the adversarial k=10 device bench config with
# nonzero prune/speculation counters.
prune: native
	JAX_PLATFORMS=cpu $(PYTHON) scripts/prune_check.py

# Fleet gate (scripts/fleet_check.py): two subprocess backends behind
# the router — SIGKILL mid-load loses zero accepted jobs, verdict parity
# with one-shot check, router /healthz 200 throughout, journal-replay
# rejoin, clean rolling drain.
fleet:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/fleet_check.py

# Fleet-telemetry gate (scripts/telemetry_check.py): two backends behind
# the router's FleetScraper — both node labels in /fleet/metrics with
# bounded cardinality, a SIGKILLed backend reading as a gap (never a
# crash or zeros), the restarted node resuming its sentinel baseline
# from the durable tsdb and still firing perf_regression, cold tsq
# agreeing with the live op, and service_bench with the recorder armed
# holding >=0.97x the published baseline.
telemetry:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/telemetry_check.py

# Distributed-search gate (scripts/distsearch_check.py): three subprocess
# backends behind the router coordinate one job sized past a single
# node's --deadline — one backend SIGKILLed mid-search, its partition
# provably re-granted under a fresh epoch, zero stale-epoch deltas
# accepted, verdict parity with the unbounded CPU oracle, grant ledger
# closed on disk.
distsearch:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/distsearch_check.py

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache s2_verification_tpu/__pycache__
