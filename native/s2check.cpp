// Native Wing–Gong linearizability checker for the S2 stream model.
//
// The reference's checking path is native (Go: golang/s2-porcupine/main.go
// driving the compiled porcupine library, go.mod:6); this is the framework's
// native-speed CPU engine, the C++ twin of checker/oracle.py:
//
//   - entries: the call/return events on a doubly-linked list
//     (oracle.py:_build_entry_list), lift/unlift in LIFO order;
//   - at each call entry, apply the powerset-lifted nondeterministic step
//     (models/stream.py:step_set; reference main.go:264-335) to the current
//     candidate state set; commit if non-empty and the (linearized-bitset,
//     state-set) pair is unseen (Lowe's memoization);
//   - a return of an unlinearized op, or falling off the list, backtracks.
//
// The chain-hash fold uses the same len==8 XXH3-64-with-seed specialization
// as ops/xxh3.py, bit-exact with the xxhash C library (pinned vectors:
// reference history.rs:687-696, main_test.go:15-32).
//
// Exposed as a C ABI consumed from Python via ctypes (checker/native.py).

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kBitflipBase = 0x1CAD21F72C81017CULL ^ 0xDB979083E96DD4DEULL;
constexpr uint64_t kPrimeMX2 = 0x9FB21C651E98DF25ULL;

inline uint64_t rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

// XXH3-64(le_bytes(value), seed), len==8 code path.
inline uint64_t xxh3_8byte_seeded(uint64_t value, uint64_t seed) {
  seed ^= static_cast<uint64_t>(__builtin_bswap32(static_cast<uint32_t>(seed)))
          << 32;
  uint64_t input64 = (value << 32) | (value >> 32);
  uint64_t h = input64 ^ (kBitflipBase - seed);
  h ^= rotl64(h, 49) ^ rotl64(h, 24);
  h *= kPrimeMX2;
  h ^= (h >> 35) + 8;  // + input length
  h *= kPrimeMX2;
  h ^= h >> 28;
  return h;
}

struct State {
  uint32_t tail;
  uint64_t hash;
  int32_t tok;  // interned fencing token id; 0 = none

  bool operator==(const State& o) const {
    return tail == o.tail && hash == o.hash && tok == o.tok;
  }
  bool operator<(const State& o) const {
    if (tail != o.tail) return tail < o.tail;
    if (hash != o.hash) return hash < o.hash;
    return tok < o.tok;
  }
};

struct Ops {
  int32_t n;
  const int32_t* op_type;
  const uint8_t* has_set_token;
  const int32_t* set_token;
  const uint8_t* has_batch_token;
  const int32_t* batch_token;
  const uint8_t* has_match;
  const uint32_t* match_seq;
  const uint32_t* num_records;
  const int32_t* rh_row;
  const int32_t* rh_len;
  int32_t rh_width;
  const uint32_t* rh_hi;
  const uint32_t* rh_lo;
  const uint8_t* out_failure;
  const uint8_t* out_definite;
  const uint32_t* out_tail;
  const uint8_t* out_has_hash;
  const uint64_t* out_hash;
};

uint64_t fold_row(const Ops& ops, int32_t j, uint64_t acc) {
  const int32_t row = ops.rh_row[j];
  const int32_t len = ops.rh_len[j];
  const uint32_t* hi = ops.rh_hi + static_cast<int64_t>(row) * ops.rh_width;
  const uint32_t* lo = ops.rh_lo + static_cast<int64_t>(row) * ops.rh_width;
  for (int32_t i = 0; i < len; ++i) {
    uint64_t rh = (static_cast<uint64_t>(hi[i]) << 32) | lo[i];
    acc = xxh3_8byte_seeded(rh, acc);
  }
  return acc;
}

// models/stream.py:step — writes 0..2 successors of `s` under op j.
// The chain-hash fold (the expensive part) only runs on branches that
// actually materialize the optimistic state.
int step_one(const Ops& ops, int32_t j, const State& s, State out[2]) {
  if (ops.op_type[j] == 0) {  // append
    const bool fail = ops.out_failure[j];
    const bool definite = ops.out_definite[j];
    if (fail && definite) {
      out[0] = s;
      return 1;
    }
    const bool tok_mismatch =
        ops.has_batch_token[j] && (s.tok == 0 || ops.batch_token[j] != s.tok);
    const bool seq_mismatch = ops.has_match[j] && ops.match_seq[j] != s.tail;
    const uint32_t opt_tail = s.tail + ops.num_records[j];
    const int32_t opt_tok =
        ops.has_set_token[j] ? ops.set_token[j] : s.tok;
    if (fail) {  // indefinite
      if (tok_mismatch || seq_mismatch) {
        out[0] = s;
        return 1;
      }
      out[0] = State{opt_tail, fold_row(ops, j, s.hash), opt_tok};
      out[1] = s;
      return 2;
    }
    // success
    if (tok_mismatch || seq_mismatch) return 0;
    if (ops.out_tail[j] != opt_tail) return 0;
    out[0] = State{opt_tail, fold_row(ops, j, s.hash), opt_tok};
    return 1;
  }
  // read / check-tail
  if (ops.out_has_hash[j] && s.hash != ops.out_hash[j]) return 0;
  if (ops.out_failure[j] || s.tail == ops.out_tail[j]) {
    out[0] = s;
    return 1;
  }
  return 0;
}

// step_set: powerset lifting, deduped, order-preserving.
std::vector<State> step_set(const Ops& ops, int32_t j,
                            const std::vector<State>& states) {
  std::vector<State> result;
  result.reserve(states.size() + 1);
  State buf[2];
  for (const State& s : states) {
    int k = step_one(ops, j, s, buf);
    for (int i = 0; i < k; ++i) {
      bool seen = false;
      for (const State& r : result)
        if (r == buf[i]) {
          seen = true;
          break;
        }
      if (!seen) result.push_back(buf[i]);
    }
  }
  return result;
}

struct CacheKey {
  std::vector<uint64_t> bits;
  std::vector<State> states;  // sorted

  bool operator==(const CacheKey& o) const {
    return bits == o.bits && states == o.states;
  }
};

uint64_t mix64(uint64_t h, uint64_t v) {
  h ^= v + 0x9E3779B97F4A7C15ULL + (h << 6) + (h >> 2);
  return h;
}

uint64_t key_hash(const CacheKey& k) {
  uint64_t h = 0xCBF29CE484222325ULL;
  for (uint64_t w : k.bits) h = mix64(h, w);
  for (const State& s : k.states) {
    h = mix64(h, s.tail);
    h = mix64(h, s.hash);
    h = mix64(h, static_cast<uint64_t>(static_cast<uint32_t>(s.tok)));
  }
  return h;
}

struct Entry {
  int32_t op;      // op index
  bool is_call;
  int32_t match;   // index of the paired entry
  int32_t prev;    // linked-list neighbor entry indices; -1 = none
  int32_t next;
};

}  // namespace

extern "C" {

// Returns 0 OK, 1 ILLEGAL, 2 UNKNOWN (time budget exhausted).
// out_order[0..*out_order_len) receives the linearization (encoded op
// indices) when OK, or the deepest linearized set reached when not.
// out_states_* receive the final candidate states when OK: *out_states_len
// is the FULL set size; only min(size, out_states_cap) entries are written
// (the caller re-invokes with a larger buffer on truncation).
//
// app_rank / inert carry the verdict-exact commutativity prunes
// (checker/prune.py); both may be null (no pruning).  app_rank[j] >= 0
// gives op j's dense position in the statically-forced successful-append
// order (-1 = unranked): ranked calls are gated until exactly their turn,
// since any other order provably never accepts.  inert[j] marks identity
// ops: once an inert op's subtree at a position is exhausted, its DFS
// siblings are skipped (sleep-set style) — any accepting order through a
// sibling reorders to commit the identity op first, which already failed.
int32_t s2_check(
    int32_t n_ops, const int32_t* op_type, const uint8_t* has_set_token,
    const int32_t* set_token, const uint8_t* has_batch_token,
    const int32_t* batch_token, const uint8_t* has_match,
    const uint32_t* match_seq, const uint32_t* num_records,
    const int32_t* rh_row, const int32_t* rh_len, int32_t rh_width,
    const uint32_t* rh_hi, const uint32_t* rh_lo, const uint8_t* out_failure,
    const uint8_t* out_definite, const uint32_t* out_tail,
    const uint8_t* out_has_hash, const uint64_t* out_hash,
    const int32_t* call_time, const int32_t* ret_time,
    const int32_t* app_rank, const uint8_t* inert, int32_t n_init,
    const uint32_t* init_tail, const uint64_t* init_hash,
    const int32_t* init_tok, double time_budget_s, int32_t* out_order,
    int32_t* out_order_len, uint32_t* out_states_tail,
    uint64_t* out_states_hash, int32_t* out_states_tok,
    int32_t out_states_cap, int32_t* out_states_len, int64_t* out_steps,
    int64_t* out_cache_hits) {
  Ops ops{n_ops,    op_type,  has_set_token, set_token, has_batch_token,
          batch_token, has_match, match_seq, num_records, rh_row,
          rh_len,   rh_width, rh_hi,         rh_lo,     out_failure,
          out_definite, out_tail, out_has_hash, out_hash};

  *out_order_len = 0;
  *out_states_len = 0;
  *out_steps = 0;
  *out_cache_hits = 0;
  std::vector<State> states;
  for (int32_t i = 0; i < n_init; ++i)
    states.push_back(State{init_tail[i], init_hash[i], init_tok[i]});
  if (n_ops == 0) {
    int32_t m = std::min<int32_t>(n_init, out_states_cap);
    for (int32_t i = 0; i < m; ++i) {
      out_states_tail[i] = states[i].tail;
      out_states_hash[i] = states[i].hash;
      out_states_tok[i] = states[i].tok;
    }
    *out_states_len = n_init;
    return 0;
  }

  // Entry list sorted by event time; pending returns (INT32_MAX) sink last.
  std::vector<Entry> entries(2 * n_ops);
  std::vector<std::pair<int64_t, int32_t>> order_idx(2 * n_ops);
  for (int32_t j = 0; j < n_ops; ++j) {
    entries[2 * j] = Entry{j, true, 2 * j + 1, -1, -1};
    entries[2 * j + 1] = Entry{j, false, 2 * j, -1, -1};
    // Tie-break on entry id keeps the sort deterministic for the
    // all-equal INT32_MAX pending returns.
    order_idx[2 * j] = {(static_cast<int64_t>(call_time[j]) << 32) | (2 * j),
                        2 * j};
    order_idx[2 * j + 1] = {
        (static_cast<int64_t>(ret_time[j]) << 32) | (2 * j + 1), 2 * j + 1};
  }
  std::sort(order_idx.begin(), order_idx.end());
  int32_t head = order_idx[0].second;
  for (size_t i = 0; i + 1 < order_idx.size(); ++i) {
    entries[order_idx[i].second].next = order_idx[i + 1].second;
    entries[order_idx[i + 1].second].prev = order_idx[i].second;
  }

  const int32_t n_words = (n_ops + 63) / 64;
  std::vector<uint64_t> bits(n_words, 0);
  // Deepest linearized set reached, for failure diagnostics (oracle.py's
  // `best`): reported through out_order on ILLEGAL/UNKNOWN.
  std::vector<uint64_t> best_bits(n_words, 0);
  size_t best_count = 0;

  std::unordered_map<uint64_t, std::vector<CacheKey>> cache;
  {
    CacheKey k0{bits, states};
    std::sort(k0.states.begin(), k0.states.end());
    cache[key_hash(k0)].push_back(std::move(k0));
  }

  struct Undo {
    int32_t call_entry;
    std::vector<State> saved_states;
  };
  std::vector<Undo> calls;
  calls.reserve(n_ops);

  // Ranked successful appends committed so far: the next one to commit
  // must be exactly rank `next_rank` (ranks are dense over the history).
  int32_t next_rank = 0;

  int64_t steps = 0, cache_hits = 0;
  const bool budgeted = time_budget_s > 0;
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                            std::chrono::duration<double>(time_budget_s));

  auto lift = [&](int32_t ce) {
    const Entry& c = entries[ce];
    int32_t re = c.match;
    const Entry& r = entries[re];
    if (c.prev >= 0) entries[c.prev].next = c.next;
    if (c.next >= 0) entries[c.next].prev = c.prev;
    if (head == ce) head = c.next;
    if (r.prev >= 0) entries[r.prev].next = r.next;
    if (r.next >= 0) entries[r.next].prev = r.prev;
    if (head == re) head = r.next;  // unreachable: call precedes return
  };
  auto unlift = [&](int32_t ce) {
    Entry& c = entries[ce];
    int32_t re = c.match;
    Entry& r = entries[re];
    if (r.prev >= 0) entries[r.prev].next = re;
    if (r.next >= 0) entries[r.next].prev = re;
    if (c.prev >= 0) entries[c.prev].next = ce;
    else head = ce;
    if (c.next >= 0) entries[c.next].prev = ce;
  };
  auto finish_stats = [&]() {
    *out_steps = steps;
    *out_cache_hits = cache_hits;
  };
  auto emit_deepest = [&]() {
    int32_t k = 0;
    for (int32_t j = 0; j < n_ops; ++j)
      if (best_bits[j >> 6] & (1ULL << (j & 63))) out_order[k++] = j;
    *out_order_len = k;
  };

  int32_t entry = head;
  while (head >= 0) {
    if (budgeted && (steps & 1023) == 0 &&
        std::chrono::steady_clock::now() > deadline) {
      finish_stats();
        emit_deepest();
      return 2;
    }
    if (entry < 0) {
      // Fell off the end: every remaining entry was an unlinearizable call.
      if (calls.empty()) {
        finish_stats();
        emit_deepest();
        return 1;
      }
      Undo u = std::move(calls.back());
      calls.pop_back();
      int32_t j = entries[u.call_entry].op;
      bits[j >> 6] &= ~(1ULL << (j & 63));
      if (app_rank && app_rank[j] >= 0) --next_rank;
      states = std::move(u.saved_states);
      unlift(u.call_entry);
      // Inert-forced backtrack: siblings of an exhausted identity op are
      // redundant (see the ABI comment) — pop straight through.
      entry = (inert && inert[j]) ? -1 : entries[u.call_entry].next;
      continue;
    }
    Entry& e = entries[entry];
    if (e.is_call) {
      int32_t j = e.op;
      if (app_rank && app_rank[j] >= 0 && app_rank[j] != next_rank) {
        // Out-of-turn ranked append: no accepting linearization commits
        // it here (successful-append tails are monotone) — skip.
        entry = e.next;
        continue;
      }
      ++steps;
      std::vector<State> ns = step_set(ops, j, states);
      if (!ns.empty()) {
        bits[j >> 6] |= 1ULL << (j & 63);
        CacheKey key{bits, ns};
        std::sort(key.states.begin(), key.states.end());
        uint64_t h = key_hash(key);
        auto& bucket = cache[h];
        bool seen = false;
        for (const CacheKey& k : bucket)
          if (k == key) {
            seen = true;
            break;
          }
        if (!seen) {
          bucket.push_back(std::move(key));
          calls.push_back(Undo{entry, std::move(states)});
          states = std::move(ns);
          if (app_rank && app_rank[j] >= 0) ++next_rank;
          lift(entry);
          if (calls.size() > best_count) {
            best_count = calls.size();
            best_bits = bits;
          }
          entry = head;
          continue;
        }
        ++cache_hits;
        bits[j >> 6] &= ~(1ULL << (j & 63));
      }
      entry = e.next;
    } else {
      // Return of an unlinearized op: must backtrack.
      if (calls.empty()) {
        finish_stats();
        emit_deepest();
        return 1;
      }
      Undo u = std::move(calls.back());
      calls.pop_back();
      int32_t j = entries[u.call_entry].op;
      bits[j >> 6] &= ~(1ULL << (j & 63));
      if (app_rank && app_rank[j] >= 0) --next_rank;
      states = std::move(u.saved_states);
      unlift(u.call_entry);
      entry = (inert && inert[j]) ? -1 : entries[u.call_entry].next;
    }
  }

  for (size_t i = 0; i < calls.size(); ++i)
    out_order[i] = entries[calls[i].call_entry].op;
  *out_order_len = static_cast<int32_t>(calls.size());
  std::sort(states.begin(), states.end());
  int32_t m = std::min<int32_t>(static_cast<int32_t>(states.size()),
                                out_states_cap);
  for (int32_t i = 0; i < m; ++i) {
    out_states_tail[i] = states[i].tail;
    out_states_hash[i] = states[i].hash;
    out_states_tok[i] = states[i].tok;
  }
  // Report the FULL size (not the clamped write count) so the caller can
  // detect truncation and re-invoke with a larger buffer.
  *out_states_len = static_cast<int32_t>(states.size());
  *out_steps = steps;
  *out_cache_hits = cache_hits;
  return 0;
}

}  // extern "C"
